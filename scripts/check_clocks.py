"""Static clock-discipline check: no wall clock in duration math.

``time.time()`` is the wrong clock for measuring durations — it jumps under
NTP slew and suspend, which is exactly how a latency percentile or an
occupancy ratio silently goes negative in a long-lived server. Everything
under ``coda_tpu/`` must time with ``time.perf_counter()`` /
``time.monotonic()``; wall-clock reads are allowed only for *timestamps*
(epoch columns in the MLflow schema) and must carry an explicit
``# wall-clock:`` pragma naming why on the same or the preceding line.

Wired into tier-1 (``tests/test_telemetry.py``) so a regressed clock fails
CI, and runnable standalone::

    python scripts/check_clocks.py [root]
"""

from __future__ import annotations

import os
import re
import sys

# wall-clock constructs that must not appear un-pragma'd: time.time() and
# naive datetime "now" reads (same jump/slew problem, different spelling)
_FORBIDDEN = re.compile(r"\btime\.time\(\)|\bdatetime\.(?:now|utcnow)\(")
_PRAGMA = "# wall-clock:"


def check_file(path: str) -> list[tuple[int, str]]:
    """(lineno, line) violations in one file."""
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    out = []
    for i, line in enumerate(lines):
        if not _FORBIDDEN.search(line):
            continue
        prev = lines[i - 1] if i > 0 else ""
        if _PRAGMA in line or _PRAGMA in prev:
            continue
        out.append((i + 1, line.rstrip()))
    return out


def check_tree(root: str) -> dict[str, list[tuple[int, str]]]:
    """{relpath: violations} over every .py file under ``root``."""
    bad = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            fp = os.path.join(dirpath, fn)
            v = check_file(fp)
            if v:
                bad[os.path.relpath(fp, root)] = v
    return bad


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "coda_tpu")
    bad = check_tree(root)
    for rel, violations in sorted(bad.items()):
        for lineno, line in violations:
            print(f"{rel}:{lineno}: wall clock in duration-capable code "
                  f"(use perf_counter/monotonic, or annotate with "
                  f"'{_PRAGMA} <reason>'): {line.strip()}")
    if bad:
        return 1
    print(f"clock check clean: no unannotated wall-clock reads under {root}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
