"""The crowd oracle subsystem contract (ISSUE 16).

  * the reliability-weighted scatter conserves row mass in EVERY branch
    (tracked, untracked-insert, untracked-absorb) under arbitrary
    weights, and the Beta reduction matches the dense weighted add;
  * ``weight=1`` is BITWISE the unweighted update — dense and sparse,
    q=1 (``update_w``) and q=8 (``update_qw``) — so a clean config can
    never drift by riding the weighted code path;
  * ``weight=0`` is a STRUCTURAL no-op on the posterior (no eviction,
    no residual motion), the all-abstain fallback;
  * the Dawid-Skene posterior recovers a planted annotator pool —
    ranking correlation against the planted diagonals, with every
    adversarial annotator ranked below every honest one;
  * ``cfg.clean`` runs the engine's own program bitwise (the crowd
    machinery never traces);
  * ``Oracle.answer_batch`` is pinned identical to the scalar loop;
  * the serve ``answer`` verb: out-of-order delivery parks and matches
    the in-order stream digest byte-for-byte, request-id dedupe makes
    redelivery idempotent and rejects conflicting payloads, abstention
    leaves the slot open, parked answers survive crash-restore, and
    ``oracle_abstain``/``oracle_poison`` inject through the front door.
"""

from __future__ import annotations

import hashlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _rand_dirichlets(key, H, C):
    return jax.random.uniform(key, (H, C, C), minval=0.05, maxval=3.0)


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if x is None or y is None:
            assert x is y
            continue
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# spec parsing + host oracle
# ---------------------------------------------------------------------------

def test_parse_oracle_spec():
    from coda_tpu.crowd.oracle import parse_oracle_spec

    assert parse_oracle_spec(None).clean
    assert parse_oracle_spec("clean").clean
    cfg = parse_oracle_spec(
        "annotators=6,votes=3,acc=0.6:0.9,abstain=0.1,adversarial=2,"
        "trust=16,defer=0.2:5,reliability=majority,seed=7")
    assert not cfg.clean
    assert (cfg.annotators, cfg.votes) == (6, 3)
    assert (cfg.acc_lo, cfg.acc_hi) == (0.6, 0.9)
    assert (cfg.abstain, cfg.adversarial, cfg.trust_votes) == (0.1, 2, 16.0)
    assert (cfg.defer, cfg.defer_depth) == (0.2, 5)
    assert cfg.reliability == "majority" and cfg.seed == 7

    for bad in ("bogus=1", "reliability=vote", "annotators=0",
                "annotators=2,adversarial=2", "abstain=1.5", "votes"):
        with pytest.raises(ValueError):
            parse_oracle_spec(bad)


def test_host_sampler_deterministic_and_attempt_readdressed():
    from coda_tpu.crowd.oracle import HostCrowdSampler, parse_oracle_spec

    cfg = parse_oracle_spec(
        "annotators=4,votes=1,abstain=0.3,defer=0.4:3,seed=5")
    s = HostCrowdSampler(cfg, n_classes=4)
    a1 = s.answer("sess", 3, 1, true_label=2)
    a2 = s.answer("sess", 3, 1, true_label=2)
    assert a1 == a2  # pure function of (session, round, slot, attempt)
    # a re-request (attempt bump) re-addresses the draws
    alts = {json.dumps(s.answer("sess", 3, 1, 2, attempt=t))
            for t in range(8)}
    assert len(alts) > 1
    # verbs stay in-protocol and labels in-range over a sweep
    for r in range(20):
        out = s.answer("x", r, 0, true_label=r % 4)
        assert out["verb"] in ("answer", "abstain")
        assert 0 <= out["label"] < 4 and 0 <= out["defer"] <= 3


def test_answer_batch_matches_scalar_loop(tiny_task):
    from coda_tpu.oracle import Oracle

    oracle = Oracle(tiny_task)
    idxs = [0, 5, 3, 5, 47, 1, 0, 12]
    got = oracle.answer_batch(idxs)
    want = [oracle(i) for i in idxs]
    assert got == want
    assert all(isinstance(v, int) for v in got)


# ---------------------------------------------------------------------------
# weighted scatter: mass conservation, w=1 bitwise, w=0 structural no-op
# ---------------------------------------------------------------------------

def test_weighted_scatter_conserves_row_mass():
    """Arbitrary per-answer weights: every row's total mass grows by
    exactly lr * sum(weights landing on it), in every branch (tracked
    hit, untracked insert-with-eviction, untracked residual-absorb) —
    so the Beta reduction matches the dense weighted add."""
    from coda_tpu.ops.beta import dirichlet_to_beta
    from coda_tpu.ops.sparse_rows import scatter_rows, sparsify, to_beta

    H, C, K, lr = 6, 12, 3, 0.7
    d = _rand_dirichlets(jax.random.PRNGKey(3), H, C)
    s = sparsify(d, K)
    rng = np.random.default_rng(0)
    q = 5
    tcs = jnp.asarray([2, 7, 2, 0, 7], jnp.int32)     # with collisions
    pcs = jnp.asarray(rng.integers(0, C, (q, H)), jnp.int32)
    ws = jnp.asarray([0.25, 1.0, 0.0, 0.6, 1.7], jnp.float32)

    s2 = scatter_rows(s, tcs, pcs, lr, weights=ws)
    mass = lambda st: (st.diag + st.vals.sum(-1) + st.resid)   # (H, C)
    inc = np.zeros((H, C), np.float32)
    for j in range(q):
        inc[:, int(tcs[j])] += lr * float(ws[j])
    np.testing.assert_allclose(np.asarray(mass(s2)),
                               np.asarray(mass(s)) + inc,
                               rtol=0, atol=1e-4)

    # Beta reduction matches the dense weighted scatter-add
    d2 = d
    for j in range(q):
        onehot = jax.nn.one_hot(pcs[j], C, dtype=d.dtype)
        d2 = d2.at[:, tcs[j], :].add(lr * ws[j] * onehot)
    a_ref, b_ref = dirichlet_to_beta(d2)
    a, b = to_beta(s2)
    np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref),
                               rtol=0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(b), np.asarray(b_ref),
                               rtol=0, atol=1e-4)


def test_weight_one_bitwise_scatter():
    """w=1 (and all-ones ws) produce bit-identical leaves to the
    unweighted path — truncated AND parity layouts, q=1 and q=8."""
    from coda_tpu.ops.sparse_rows import scatter_row, scatter_rows, sparsify

    H, C = 5, 10
    d = _rand_dirichlets(jax.random.PRNGKey(4), H, C)
    rng = np.random.default_rng(1)
    q = 8
    tcs = jnp.asarray(rng.integers(0, C, (q,)), jnp.int32)
    pcs = jnp.asarray(rng.integers(0, C, (q, H)), jnp.int32)
    ones = jnp.ones((q,), jnp.float32)
    for k in (3, C):
        s = sparsify(d, k)
        _leaves_equal(
            scatter_row(s, tcs[0], pcs[0], 0.5, weight=jnp.float32(1.0)),
            scatter_row(s, tcs[0], pcs[0], 0.5))
        _leaves_equal(scatter_rows(s, tcs, pcs, 0.5, weights=ones),
                      scatter_rows(s, tcs, pcs, 0.5))


def test_weight_zero_structural_noop():
    """w=0 leaves every posterior leaf bitwise untouched — including the
    index leaf (no eviction on the strength of the residual share)."""
    from coda_tpu.ops.sparse_rows import scatter_row, sparsify

    H, C = 5, 10
    d = _rand_dirichlets(jax.random.PRNGKey(5), H, C)
    s = sparsify(d, 3)
    rng = np.random.default_rng(2)
    for tc in range(C):
        pc = jnp.asarray(rng.integers(0, C, (H,)), jnp.int32)
        s0 = scatter_row(s, jnp.int32(tc), pc, 0.5,
                         weight=jnp.float32(0.0))
        _leaves_equal(s0, s)


def test_weight_one_bitwise_selector_dense_and_sparse(tiny_task):
    """The selector-level pin: update_w(w=1) == update and
    update_qw(ones) == update_q on real CODA states, dense and sparse."""
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.batch import resolve_batch_fns, resolve_batch_wfns

    task = tiny_task
    q = 8
    rng = np.random.default_rng(3)
    idxs = jnp.asarray(rng.choice(task.preds.shape[1], q, replace=False),
                       jnp.int32)
    tcs = jnp.asarray(rng.integers(0, 4, (q,)), jnp.int32)
    probs = jnp.full((q,), 0.5, jnp.float32)
    for posterior in ("dense", "sparse:4"):
        sel = make_coda(task.preds, CODAHyperparams(
            eig_chunk=64, num_points=64, posterior=posterior))
        state = sel.init(jax.random.PRNGKey(0))
        # q=1
        s_w = sel.update_w(state, idxs[0], tcs[0], probs[0],
                           jnp.float32(1.0))
        s_u = sel.update(state, idxs[0], tcs[0], probs[0])
        _leaves_equal(s_w, s_u)
        # q=8 fused
        _, upd_qw = resolve_batch_wfns(sel, q)
        _, upd_q = resolve_batch_fns(sel, q)
        _leaves_equal(upd_qw(state, idxs, tcs, probs, jnp.ones((q,))),
                      upd_q(state, idxs, tcs, probs))


# ---------------------------------------------------------------------------
# the reliability posterior
# ---------------------------------------------------------------------------

def test_ds_recovers_planted_confusions():
    """300 rounds of votes from a seeded pool (2 adversaries): the
    learned accuracies rank-correlate with the planted diagonals and
    every adversary ranks below every honest annotator."""
    from coda_tpu.crowd.oracle import (
        make_annotators,
        parse_oracle_spec,
        planted_accuracies,
        sample_votes,
    )
    from coda_tpu.crowd.reliability import (
        aggregate_votes,
        annotator_accuracy,
        init_reliability,
    )

    cfg = parse_oracle_spec(
        "annotators=8,votes=3,acc=0.55:0.95,abstain=0.05,adversarial=2,"
        "trust=24,seed=1")
    C = 4
    conf = make_annotators(cfg, C)
    rel0 = init_reliability(cfg, C)
    kz, kv = jax.random.split(jax.random.PRNGKey(0))
    rounds = 300
    zs = jax.random.randint(kz, (rounds,), 0, C, dtype=jnp.int32)

    def step(rel, inp):
        z, k = inp
        ann, resp, ans = sample_votes(k, conf, z, cfg)
        label, w, rel2 = aggregate_votes(rel, ann, resp, ans, cfg)
        return rel2, (label, w)

    keys = jax.random.split(kv, rounds)
    rel, (labels, ws) = jax.lax.scan(step, rel0, (zs, keys))

    learned = np.asarray(annotator_accuracy(rel))
    planted = planted_accuracies(cfg)
    adv = np.zeros(cfg.annotators, bool)
    adv[-cfg.adversarial:] = True
    planted_diag = np.where(
        adv, (1.0 - planted) / (C - 1), planted)  # true-diagonal accuracy
    corr = float(np.corrcoef(learned, planted_diag)[0, 1])
    assert corr > 0.9, (corr, learned, planted_diag)
    assert learned[adv].max() < learned[~adv].min()
    # aggregation is materially better than chance, weights in [0, 1]
    acc = float((np.asarray(labels) == np.asarray(zs)).mean())
    assert acc > 0.5, acc
    w_np = np.asarray(ws)
    assert (w_np >= 0).all() and (w_np <= 1).all()


def test_crowd_clean_pin_bitwise(tiny_task):
    """cfg.clean runs the engine's own program — same functions, same
    closed-over losses, bit-identical results (the crowd machinery never
    traces)."""
    from coda_tpu.crowd.loop import build_crowd_experiment_fn
    from coda_tpu.crowd.oracle import parse_oracle_spec
    from coda_tpu.engine.loop import build_experiment_fn
    from coda_tpu.oracle import true_losses
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = tiny_task
    sel = make_coda(task.preds, CODAHyperparams(eig_chunk=64,
                                                num_points=64))
    losses = true_losses(task.preds, task.labels)
    base = build_experiment_fn(sel, task.labels, losses, iters=6)
    crowd = build_crowd_experiment_fn(sel, task.labels, losses,
                                      parse_oracle_spec("clean"), iters=6)
    keys = jnp.stack([jax.random.PRNGKey(s) for s in range(2)])
    want = jax.jit(jax.vmap(base))(keys)
    got, aux = jax.jit(jax.vmap(crowd))(keys)
    assert aux is None
    _leaves_equal(got, want)


def test_crowd_noisy_loop_runs(tiny_task):
    """A noisy config traces, scans, and reports in-protocol aux."""
    from coda_tpu.crowd.loop import run_seeds_crowd
    from coda_tpu.crowd.oracle import parse_oracle_spec
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = tiny_task
    cfg = parse_oracle_spec(
        "annotators=4,votes=3,abstain=0.2,adversarial=1,trust=8,seed=0")
    res, aux = run_seeds_crowd(
        lambda p: make_coda(p, CODAHyperparams(eig_chunk=64,
                                               num_points=64)),
        task.preds, task.labels, cfg, iters=6, seeds=2)
    assert aux is not None
    assert aux.applied_label.shape == (2, 6)
    w = np.asarray(aux.label_weight)
    assert (w >= 0).all() and (w <= 1).all()
    assert aux.annotator_accuracy.shape == (2, 6, 4)
    assert np.asarray(res.cumulative_regret).shape == (2, 6)


# ---------------------------------------------------------------------------
# the serve answer verb (park / dedupe / abstain / restore / faults)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def answer_scenario(tmp_path_factory):
    """One full out-of-order answer choreography (module-scoped: the
    warm-pool builds dominate, so every assertion rides one run)."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.serve import recovery
    from coda_tpu.serve.faults import FaultInjector
    from coda_tpu.serve.server import ServeApp
    from coda_tpu.serve.state import SelectorSpec
    from coda_tpu.telemetry import SessionRecorder

    tmp = tmp_path_factory.mktemp("crowd_serve")
    task = make_synthetic_task(0, H=8, N=64, C=4)

    def mkapp(record_dir):
        app = ServeApp(capacity=3, max_wait=0.001,
                       spec=SelectorSpec.create("coda", n_parallel=3,
                                                acq_batch=3),
                       recorder=SessionRecorder(out_dir=str(record_dir)))
        app.add_task("t", task.preds)
        app.start()
        return app

    facts = {}
    rd = tmp / "rec"
    app = mkapp(rd)
    r = app.open_session("t", seed=0)
    sid = r["session"]

    # round 0 (q=3) delivered out of order: slots 2, 0, then 1 completes
    facts["park2"] = app.answer(sid, 2, label=1, request_id="a2")
    facts["park0"] = app.answer(sid, 0, label=0, request_id="a0")
    facts["park_dup"] = app.answer(sid, 2, label=1, request_id="a2")
    try:
        app.answer(sid, 2, label=3, request_id="zz")
        facts["conflict_raised"] = False
    except ValueError:
        facts["conflict_raised"] = True
    facts["complete"] = app.answer(sid, 1, label=2, request_id="a1")
    facts["n_after_round0"] = app.store.get(sid).n_labeled
    facts["late_dup"] = app.answer(sid, 0, label=0, request_id="a0")
    facts["abstain"] = app.answer(sid, 1, abstain=True)
    # round 1: park two answers, then crash-restore mid-round
    app.answer(sid, 1, label=3, request_id="b1")
    app.answer(sid, 0, label=1, request_id="b0")
    facts["metrics"] = app.metrics.snapshot()["oracle"]

    app2 = mkapp(rd)
    rep = recovery.restore_app_sessions(app2, str(rd))
    facts["restored"] = sid in rep["restored"]
    s2 = app2.store.get(sid)
    facts["restored_n"] = s2.n_labeled
    facts["restored_parked"] = {j: dict(e) for j, e in s2.parked.items()}
    facts["finish"] = app2.answer(sid, 2, label=0, request_id="b2")
    facts["final_n"] = app2.store.get(sid).n_labeled

    # the same labels delivered IN order on a fresh app
    app3 = mkapp(tmp / "rec3")
    sid3 = app3.open_session("t", seed=0)["session"]
    for rnd, labs in enumerate([[0, 2, 1], [1, 3, 0]]):
        for j, lab in enumerate(labs):
            app3.answer(sid3, j, label=lab, request_id=f"r{rnd}s{j}")

    def digest(a, s):
        rows = recovery.data_rows(a.recorder.history(s))
        keys = ("n_labeled", "labeled_idx", "label", "next_idx",
                "next_prob", "best", "pbest_max")
        return hashlib.sha256(json.dumps(
            [{k: r.get(k) for k in keys} for r in rows],
            sort_keys=True).encode()).hexdigest()

    facts["digest_ooo"] = digest(app2, sid)
    facts["digest_ino"] = digest(app3, sid3)

    # fault injection through the front door
    app3.faults = FaultInjector("oracle_abstain:after=0;oracle_poison:after=1")
    facts["fault_abstain"] = app3.answer(sid3, 0, label=1, request_id="f0")
    facts["fault_poison"] = app3.answer(sid3, 0, label=1, request_id="f1")
    facts["poisoned_label"] = app3.store.get(sid3).parked[0]["label"]
    return facts


def test_answer_out_of_order_parks_then_dispatches(answer_scenario):
    f = answer_scenario
    assert f["park2"]["verb"] == "parked" and f["park2"]["missing"] == [0, 1]
    assert f["park0"]["verb"] == "parked"
    assert f["complete"]["verb"] == "dispatched"
    assert f["complete"]["applied"] == [0, 2, 1]  # slot order, not arrival
    assert f["n_after_round0"] == 3


def test_answer_request_id_dedupe(answer_scenario):
    f = answer_scenario
    # redelivery of a parked answer is idempotent
    assert f["park_dup"]["verb"] == "parked" and f["park_dup"]["duplicate"]
    # a conflicting request-id on a parked slot is a double-apply reject
    assert f["conflict_raised"]
    # redelivery AFTER the round committed reads the committed result
    assert f["late_dup"]["verb"] == "committed" and f["late_dup"]["duplicate"]
    m = answer_scenario["metrics"]
    assert m["double_apply_rejects"] == 1


def test_answer_abstain_and_metrics(answer_scenario):
    f = answer_scenario
    assert f["abstain"]["verb"] == "abstain"
    m = f["metrics"]
    assert m["abstentions"] == 1
    assert m["deferred_rounds_completed"] == 1
    assert m["reorder_depth_max"] == 1  # slot 0 arrived after slot 2


def test_answer_crash_restore_reparks(answer_scenario):
    f = answer_scenario
    assert f["restored"] and f["restored_n"] == 3
    assert sorted(f["restored_parked"]) == [0, 1]
    assert f["restored_parked"][1]["label"] == 3
    assert f["finish"]["verb"] == "dispatched"
    assert f["finish"]["applied"] == [1, 3, 0]
    assert f["final_n"] == 6


def test_answer_out_of_order_matches_in_order_digest(answer_scenario):
    f = answer_scenario
    assert f["digest_ooo"] == f["digest_ino"]


def test_answer_fault_injection(answer_scenario):
    f = answer_scenario
    assert f["fault_abstain"]["verb"] == "abstain"
    assert f["fault_poison"]["verb"] == "parked"
    assert f["poisoned_label"] == 2  # (1 + 1) % 4
