"""Tier-1 wiring of the committed-artifact perf gate + evidence manifest.

``scripts/check_perf.py`` is the generalized descendant of
``check_serve_bench.py``: a declarative contract registry over every
``BENCH_*``/``EVIDENCE_*`` artifact at the repo root. These tests pin the
gate's three promises:

  * the committed artifact set passes clean (regenerating an artifact
    weaker — or adding one with no declared contract — fails tier-1);
  * tampering a gated bound or deleting a required field is caught;
  * the fingerprint policy grandfathers pre-r11 artifacts EXPLICITLY
    (recorded note, never silence) while new rounds must stamp, and the
    same-fingerprint cross-round regression comparison fires on a
    regressed re-capture.

Plus the capture half: ``scripts/capture_evidence.py``'s manifest format
satisfies the EVIDENCE contract it will be held to.
"""

from __future__ import annotations

import copy
import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    # dataclass field-annotation resolution looks the module up by name
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def check_perf():
    return _load("check_perf")


# ---------------------------------------------------------------------------
# the committed set
# ---------------------------------------------------------------------------

def test_committed_artifacts_pass_the_gate(check_perf):
    """Every BENCH_*/EVIDENCE_* at the repo root has a contract and
    satisfies it — the tier-1 gate itself."""
    notes: list = []
    violations = check_perf.check_root(REPO, notes)
    assert violations == []
    # the gate saw the whole artifact set, not an empty glob
    assert len(check_perf.discover(REPO)) >= 28
    # pre-r11 artifacts pass via the EXPLICIT grandfather note, and at
    # least one (the serve r09 capture) is recorded as fingerprint: null
    assert any("BENCH_SERVE_CPU_r09" in n and "null" in n for n in notes)


def test_every_root_artifact_matches_exactly_one_contract(check_perf):
    for path in check_perf.discover(REPO):
        assert check_perf.match_contract(path) is not None, path


def test_unregistered_artifact_fails(check_perf, tmp_path):
    """A BENCH_ file with no contract entry must fail the root gate —
    new artifacts have to declare their claim to land."""
    shutil.copy(os.path.join(REPO, "BENCH_SERVE_CPU_r09.json"),
                tmp_path / "BENCH_SERVE_CPU_r09.json")
    (tmp_path / "BENCH_MYSTERY_r99.json").write_text("{}")
    violations = check_perf.check_root(str(tmp_path))
    assert any("BENCH_MYSTERY_r99.json" in v and "no contract" in v
               for v in violations)


# ---------------------------------------------------------------------------
# tamper detection
# ---------------------------------------------------------------------------

def _check_one(check_perf, name: str, report: dict) -> list:
    contract = check_perf.match_contract(name)
    assert contract is not None
    return check_perf.check_artifact(name, report, contract)


def test_tampered_bound_fails(check_perf):
    with open(os.path.join(REPO, "BENCH_SERVE_CPU_r09.json")) as f:
        report = json.load(f)
    name = "BENCH_SERVE_CPU_r09.json"
    assert _check_one(check_perf, name, report) == []

    bad = copy.deepcopy(report)
    bad["latency_ms"]["p99"] = check_perf.P99_MS_MAX + 1
    assert any("p99" in v for v in _check_one(check_perf, name, bad))
    bad = copy.deepcopy(report)
    bad["n_errors"] = 3
    assert any("n_errors" in v for v in _check_one(check_perf, name, bad))


def test_deleted_required_field_fails(check_perf):
    # one representative per contract family with a committed bound
    cases = [
        ("BENCH_SERVE_CPU_r09.json", "breakdown"),
        ("BENCH_SUITE_CPU_FULL_r04.json", "pairs"),
        ("BENCH_TPU_HEADLINE_r05_default.json", "timing"),
        ("BENCH_RECORDER_CPU_r08.json", "bound"),
        ("BENCH_r03.json", "parsed"),
    ]
    for fname, field in cases:
        with open(os.path.join(REPO, fname)) as f:
            report = json.load(f)
        assert _check_one(check_perf, fname, report) == [], fname
        bad = copy.deepcopy(report)
        del bad[field]
        assert _check_one(check_perf, fname, bad) != [], (fname, field)


def test_linearity_and_recorder_bounds_gate(check_perf):
    """The non-serve bounds actually bite: a headline capture whose
    linearity guard failed, and a recorder config over its committed
    overhead bound, are both rejected."""
    with open(os.path.join(REPO, "BENCH_TPU_HEADLINE_r05_default.json")) as f:
        head = json.load(f)
    bad = copy.deepcopy(head)
    bad["timing"]["linearity"]["ok"] = False
    assert any("linearity" in v for v in _check_one(
        check_perf, "BENCH_TPU_HEADLINE_r05_default.json", bad))

    with open(os.path.join(REPO, "BENCH_RECORDER_CPU_r08.json")) as f:
        rec = json.load(f)
    bad = copy.deepcopy(rec)
    bad["configs"][0]["overhead"] = bad["bound"] + 0.01
    assert any("overhead" in v for v in _check_one(
        check_perf, "BENCH_RECORDER_CPU_r08.json", bad))


# ---------------------------------------------------------------------------
# fingerprint policy + cross-round regression
# ---------------------------------------------------------------------------

def _fp(knobs=None, backend="cpu"):
    return {"backend": backend, "jax_version": "0.4.x",
            "jaxlib_version": "0.4.x", "device_kind": "cpu",
            "n_devices": 1, "threefry_partitionable": True, "x64": False,
            "knobs": dict(knobs or {}), "dataset": {}}


def _suite_report(value: float, fp=None) -> dict:
    rep = {"metric": "suite", "value": value, "unit": "s",
           "total_wall": value, "pairs": [{"task": "t", "method": "iid"}],
           "per_method_s": {"iid": value}}
    if fp is not None:
        rep["fingerprint"] = fp
    return rep


def test_new_round_requires_fingerprint(check_perf):
    """An r11+ artifact without the environment stamp fails; the same
    artifact stamped passes."""
    name = "BENCH_SUITE_CPU_SMOKE_r12.json"
    vs = _check_one(check_perf, name, _suite_report(10.0))
    assert any("fingerprint" in v for v in vs)
    assert _check_one(check_perf, name, _suite_report(10.0, _fp())) == []


def test_cross_round_regression_same_fingerprint(check_perf, tmp_path):
    """Two suite captures with the SAME fingerprint (environment + knobs):
    a newer round regressed past the explicit tolerance fails, within it
    passes; a knob change (different workload) never compares."""
    fp = _fp({"methods": "iid", "seeds": 2})
    contract = check_perf.match_contract("BENCH_SUITE_X_r11.json")

    def triples(new_value, new_fp):
        return [
            ("BENCH_SUITE_X_r11.json", _suite_report(100.0, fp), contract),
            ("BENCH_SUITE_X_r12.json", _suite_report(new_value, new_fp),
             contract),
        ]

    # lower-is-better metric: +50% wall regresses past the 25% tolerance
    bad = check_perf.cross_round_violations(triples(150.0, fp))
    assert any("regressed" in v and "r11" in v for v in bad)
    # within tolerance: clean (and noted)
    notes: list = []
    assert check_perf.cross_round_violations(triples(110.0, fp),
                                             notes) == []
    assert any("within" in n for n in notes)
    # different knobs -> different fingerprint key -> never compared
    other = _fp({"methods": "coda", "seeds": 5})
    assert check_perf.cross_round_violations(triples(900.0, other)) == []
    # fingerprint-less artifacts never compare (grandfather semantics)
    assert check_perf.cross_round_violations(
        [("BENCH_SUITE_X_r11.json", _suite_report(100.0), contract),
         ("BENCH_SUITE_X_r12.json", _suite_report(900.0, fp), contract)]
    ) == []


# ---------------------------------------------------------------------------
# the evidence manifest format
# ---------------------------------------------------------------------------

def _component(report, status="ok"):
    return {"status": status, "wall_s": 1.0, "report": report}


def _manifest(check_perf, capture_evidence, tweak=None):
    fp = _fp({"capture": "quick"})
    serve = {"bench": "serve_loadgen", "n_errors": 0,
             "latency_ms": {"p50": 10.0, "p99": 50.0},
             "fingerprint": _fp({"sessions": 8})}
    comps = {
        "bench": _component({"value": 12.3,
                             "fingerprint": _fp({"small": True})}),
        "bench_suite": _component(_suite_report(9.0, _fp({"s": 2}))),
        "serve_loadgen": _component(serve),
        "multichip_replay": _component({"ok": True, "configs": []}),
    }
    man = capture_evidence.build_manifest("r99", fp, comps, quick=True)
    if tweak:
        tweak(man)
    return man


def test_capture_manifest_passes_the_evidence_contract(check_perf):
    capture_evidence = _load("capture_evidence")
    man = _manifest(check_perf, capture_evidence)
    name = "EVIDENCE_cpu_r99.json"
    assert _check_one(check_perf, name, man) == []
    # every own-stamped component was fingerprint-verified against the
    # manifest environment; the dryrun (no own stamp) inherits, recorded
    arts = man["artifacts"]
    assert arts["bench"]["fingerprint_match"] is True
    assert arts["multichip_replay"]["fingerprint_inherited"] is True

    # a failed component fails the manifest
    bad = _manifest(check_perf, capture_evidence, lambda m: m["artifacts"][
        "bench_suite"].update(status="failed:rc=1"))
    assert any("bench_suite" in v for v in _check_one(check_perf, name,
                                                      bad))
    # a component captured in a different environment fails it
    def cross_env(m):
        m["artifacts"]["bench"]["report"]["fingerprint"]["backend"] = "tpu"
        m["artifacts"]["bench"]["fingerprint_match"] = \
            capture_evidence.fingerprint_match(
                m["fingerprint"],
                m["artifacts"]["bench"]["report"]["fingerprint"])
    bad = _manifest(check_perf, capture_evidence, cross_env)
    assert any("different environment" in v
               for v in _check_one(check_perf, name, bad))
    # serve errors fail it
    bad = _manifest(check_perf, capture_evidence, lambda m: m["artifacts"][
        "serve_loadgen"]["report"].update(n_errors=3))
    assert any("n_errors" in v for v in _check_one(check_perf, name, bad))


def test_committed_evidence_manifest_gated(check_perf):
    """The committed EVIDENCE_* capture(s) pass their contract — and the
    gate refuses an unstamped one."""
    import glob

    paths = glob.glob(os.path.join(REPO, "EVIDENCE_*.json"))
    assert paths, "no committed evidence manifest at the repo root"
    for path in paths:
        with open(path) as f:
            man = json.load(f)
        assert _check_one(check_perf, os.path.basename(path), man) == [], \
            path
        bad = copy.deepcopy(man)
        bad.pop("fingerprint")
        assert _check_one(check_perf, os.path.basename(path), bad) != []


def test_check_perf_cli_gates_root():
    """The standalone invocation the docs cite exits 0 on the committed
    tree."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_perf.py")],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "perf gate clean" in proc.stdout


@pytest.mark.slow
def test_capture_evidence_quick_end_to_end(tmp_path):
    """The acceptance run: one invocation of capture_evidence --quick on
    the CPU container produces a schema-valid manifest that passes
    check_perf. Slow (four subprocess captures) — excluded from tier-1;
    the committed manifest keeps the fast gate honest."""
    out = tmp_path / "EVIDENCE_cpu_r98.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "capture_evidence.py"),
         "--quick", "--round", "r98", "--out", str(out),
         "--platform", "cpu"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=3000)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-4000:]
    check_perf = _load("check_perf")
    with open(out) as f:
        man = json.load(f)
    contract = check_perf.match_contract(str(out))
    assert check_perf.check_artifact(str(out), man, contract) == []
