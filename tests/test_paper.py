"""Tests for the paper analysis suite (SQL load, table/figure generation)."""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys

import numpy as np
import pytest

PAPER = os.path.join(os.path.dirname(__file__), "..", "paper")


def _load(name):
    sys.path.insert(0, PAPER)
    try:
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(PAPER, name + ".py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    finally:
        sys.path.remove(PAPER)


@pytest.fixture()
def bench_db(tmp_path):
    """A DB with 2 tasks x 2 methods x 2 seeds of regret traces."""
    from coda_tpu.tracking import TrackingStore

    db = str(tmp_path / "db.sqlite")
    store = TrackingStore(db)
    curves = {
        # coda converges (regret < 1% from step 2), iid doesn't
        "coda-lr=0.01-mult=2.0-no-prefilter": [2.0, 0.5, 0.2, 0.0],
        "iid": [5.0, 4.0, 3.0, 2.0],
    }
    for task in ("cifar10_5592", "pacs"):
        for method, curve in curves.items():
            with store.run(task, f"{task}-{method}") as parent:
                for s in range(2):
                    noise = 0.1 * s
                    with store.run(task, f"{task}-{method}-{s}",
                                   parent=parent) as r:
                        r.log_metric_series(
                            "regret", [(v + noise) / 100 for v in curve],
                            start_step=1)
                        r.log_metric_series(
                            "cumulative regret",
                            list(np.cumsum([(v + noise) / 100 for v in curve])),
                            start_step=1)
    store.close()
    return db


def test_load_metric_and_canonicalization(bench_db):
    common = _load("common")
    df = common.load_metric(bench_db, "regret")
    assert set(df.method) == {"CODA (Ours)", "Random Sampling"}
    assert set(df.task) == {"cifar10_5592", "pacs"}
    # seed-mean x100: step 1 coda = mean(2.0, 2.1)
    row = df[(df.task == "pacs") & (df.method == "CODA (Ours)")
             & (df.step == 1)]
    np.testing.assert_allclose(row["value"].iloc[0], 2.05, rtol=1e-6)


def test_load_metric_at_step(bench_db):
    common = _load("common")
    df = common.load_metric(bench_db, "cumulative regret", step=4)
    assert set(df.step) == {4}


def test_tab1_latex(bench_db):
    common = _load("common")
    tab1 = _load("tab1")
    df = common.load_metric(bench_db, "cumulative regret", step=4)
    latex = tab1.build_table(df)
    assert r"\begin{tabular}" in latex and r"\bottomrule" in latex
    assert "cifar10-high" in latex and "pacs" in latex
    # coda has the lower cumulative regret -> bold inside its gray cell
    assert r"\cellcolor{gray!15}\textbf{" in latex


def test_fig1_convergence_logic(bench_db):
    common = _load("common")
    fig1 = _load("fig1")
    df = common.load_metric(bench_db, "regret")
    methods = ["Random Sampling", "CODA (Ours)"]
    tasks = ["cifar10_5592", "pacs"]
    conv = fig1.convergence_steps(df, methods, tasks, threshold=1.0,
                                  max_steps=4)
    assert conv["CODA (Ours)"]["pacs"] == 2
    assert conv["Random Sampling"]["pacs"] == fig1.NO_CONVERGENCE
    prop = fig1.proportions(conv, methods, tasks, max_steps=4)
    np.testing.assert_allclose(prop["CODA (Ours)"], [0, 1, 1, 1])
    np.testing.assert_allclose(prop["Random Sampling"], [0, 0, 0, 0])


@pytest.mark.parametrize("script,extra", [
    ("tab1.py", ["--step", "4"]),
    ("fig1.py", ["--max-steps", "4"]),
    ("fig3.py", []),
    ("fig5.py", []),
])
def test_paper_scripts_end_to_end(bench_db, tmp_path, script, extra):
    out = str(tmp_path / ("out." + ("tex" if script == "tab1.py" else "pdf")))
    r = subprocess.run(
        [sys.executable, os.path.join(PAPER, script), "--db", bench_db,
         "--out", out] + extra,
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    assert os.path.exists(out)


def test_fig4_probe(tmp_path):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from coda_tpu.data import make_synthetic_task

    fig4 = _load("fig4")
    task = make_synthetic_task(seed=0, H=3, N=40, C=3)
    np.save(str(tmp_path / "t.npy"), np.asarray(task.preds))
    np.save(str(tmp_path / "t_labels.npy"), np.asarray(task.labels))
    fig, axes = plt.subplots(1, 2)
    fig4.probe_task(str(tmp_path / "t.npy"), axes[0], axes[1], "t")
    plt.close(fig)


def test_load_metric_excludes_nan_and_accepts_bare_coda(tmp_path):
    from coda_tpu.tracking import TrackingStore

    common = _load("common")
    db = str(tmp_path / "db2.sqlite")
    store = TrackingStore(db)
    with store.run("t1", "t1-coda") as parent:
        with store.run("t1", "t1-coda-0", parent=parent) as r:
            r.log_metric_series("regret", [0.5, float("nan"), 0.3],
                                start_step=1)
    store.close()
    df = common.load_metric(db, "regret")
    # bare "coda" is the canonical config
    assert set(df.method) == {"CODA (Ours)"}
    # the NaN step is excluded, not read as 0.0
    assert sorted(df.step) == [1, 3]
