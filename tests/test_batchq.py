"""Batched top-q acquisition (--acq-batch): the ISSUE 12 contract.

  * q=1 is BITWISE the legacy single-label program for every selector
    (trajectory + recorder arrays), pinned on the real-digits trace for
    CODA and on synthetic tasks for the rest;
  * the fused multi-row sparse scatter conserves row mass exactly and
    matches q sequential ``scatter_row`` applications bitwise — including
    two answers landing on the same class row in one batch;
  * q-wide records roundtrip at schema v2 and replay bitwise through the
    identical q-wide program; q-vs-1 comparisons triage through the
    knob-diff/regret-envelope path; old record versions stay loadable,
    old SESSION streams are version-gated with the real reason;
  * the serve batch-label verb applies a round's q answers exactly once
    under concurrent retries sharing a request_id, and q-wide sessions
    export/import with bitwise stream replay.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from coda_tpu.data import make_synthetic_task  # noqa: E402
from coda_tpu.engine.loop import (  # noqa: E402
    run_seeds_compiled,
    run_seeds_recorded,
)
from coda_tpu.ops.sparse_rows import (  # noqa: E402
    SparseRows,
    scatter_row,
    scatter_rows,
    sparsify,
)
from coda_tpu.selectors import (  # noqa: E402
    CODAHyperparams,
    make_activetesting,
    make_coda,
    make_iid,
    make_modelpicker,
    make_uncertainty,
    make_vma,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def task():
    return make_synthetic_task(seed=0, H=6, N=120, C=5)


def _factories():
    return {
        "coda": lambda p: make_coda(p, CODAHyperparams()),
        "coda_sparse": lambda p: make_coda(
            p, CODAHyperparams(posterior="sparse:3")),
        "model_picker": lambda p: make_modelpicker(p, epsilon=0.4),
        "activetesting": lambda p: make_activetesting(p, budget=64),
        "vma": lambda p: make_vma(p, budget=64),
        "iid": lambda p: make_iid(p),
        "uncertainty": lambda p: make_uncertainty(p),
    }


# ---------------------------------------------------------------------------
# q=1 bitwise-equals-legacy pin, every selector
# ---------------------------------------------------------------------------

def test_acq_batch_one_is_bitwise_legacy_every_selector(task):
    """``acq_batch=1`` runs the UNCHANGED single-label program: results
    and recorder arrays are bitwise the default invocation's."""
    for name, fac in _factories().items():
        res_legacy, aux_legacy = run_seeds_recorded(
            fac, task.preds, task.labels, iters=6, seeds=2, trace_k=4)
        res_q1, aux_q1 = run_seeds_recorded(
            fac, task.preds, task.labels, iters=6, seeds=2, trace_k=4,
            acq_batch=1)
        for a, b in zip(jax.tree.leaves((res_legacy, aux_legacy)),
                        jax.tree.leaves((res_q1, aux_q1))):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), name


@pytest.mark.slow
def test_acq_batch_one_bitwise_on_real_digits():
    """The acceptance pin at full fidelity: the real-digits CODA trace."""
    from coda_tpu.cli import load_dataset
    import argparse

    ds = load_dataset(argparse.Namespace(
        task="digits", data_dir=os.path.join(REPO, "data"),
        synthetic=None, mesh=None))
    fac = lambda p: make_coda(p, CODAHyperparams())  # noqa: E731
    a = run_seeds_recorded(fac, ds.preds, ds.labels, iters=30, seeds=2)
    b = run_seeds_recorded(fac, ds.preds, ds.labels, iters=30, seeds=2,
                           acq_batch=1)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes()


def test_batched_picks_are_distinct_and_budgeted(task):
    """Every selector's q-wide round picks q DISTINCT points, and the
    label budget validation counts rounds*q."""
    for name, fac in _factories().items():
        res = run_seeds_compiled(fac, task.preds, task.labels, iters=4,
                                 seeds=2, acq_batch=4)
        ci = np.asarray(res.chosen_idx)
        assert ci.shape == (2, 4, 4), name
        for s in range(2):
            flat = ci[s].reshape(-1).tolist()
            assert len(set(flat)) == len(flat), (name, flat)
    with pytest.raises(ValueError, match="exceeds the"):
        run_seeds_compiled(_factories()["iid"], task.preds, task.labels,
                           iters=40, seeds=1, acq_batch=4)  # 160 > 120
    with pytest.raises(ValueError, match="fixed label buffer"):
        run_seeds_compiled(
            lambda p: make_activetesting(p, budget=8),
            task.preds, task.labels, iters=4, seeds=1, acq_batch=4)


def test_activetesting_update_q_ring_edge_drops_like_q1(task):
    """A q-wide batch straddling the LURE ring-buffer edge (a serving
    session past its budget) DROPS the out-of-range columns exactly like
    q sequential q=1 updates — never a clamped block write that would
    overwrite committed history."""
    sel = make_activetesting(task.preds, budget=6)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    state = state._replace(n_labeled=jnp.asarray(4, jnp.int32))
    idxs = jnp.asarray([0, 1, 2, 3], jnp.int32)
    tcs = jnp.asarray([1, 1, 0, 2], jnp.int32)
    probs = jnp.asarray([0.1, 0.2, 0.3, 0.4], jnp.float32)
    fused = jax.jit(sel.update_q)(state, idxs, tcs, probs)
    seq = state
    for j in range(4):
        seq = sel.update(seq, idxs[j], tcs[j], probs[j])
    for a, b in zip(jax.tree.leaves(fused), jax.tree.leaves(seq)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    # slots 4-5 took the first two answers; 6-7 fell off the ring
    assert np.asarray(fused.qs)[4] == np.float32(0.1)
    assert np.asarray(fused.qs)[5] == np.float32(0.2)
    assert int(fused.n_labeled) == 8


def test_label_weighted_cumulative_regret(task):
    """q>1 rounds weight cumulative regret by their q labels, so budgets
    align with q=1 runs: cum[t] == q * cumsum(regret)[t]."""
    fac = _factories()["model_picker"]
    res = run_seeds_compiled(fac, task.preds, task.labels, iters=5,
                             seeds=1, acq_batch=4)
    regret = np.asarray(res.regret)[0]
    cum = np.asarray(res.cumulative_regret)[0]
    np.testing.assert_allclose(cum, 4.0 * np.cumsum(regret), rtol=1e-6)


# ---------------------------------------------------------------------------
# multi-row sparse scatter
# ---------------------------------------------------------------------------

def _random_sparse(H=5, C=7, K=3, seed=0):
    rng = np.random.default_rng(seed)
    dense = jnp.asarray(rng.uniform(0.05, 2.0, (H, C, C)).astype(
        np.float32))
    return sparsify(dense, K), H, C


def _row_masses(s: SparseRows) -> np.ndarray:
    return np.asarray(s.diag + s.vals.sum(-1)
                      + (0.0 if s.full else s.resid))


def test_scatter_rows_matches_sequential_bitwise():
    """The fused multi-row scatter is bitwise q sequential scatter_row
    applications — including a within-batch same-row collision, which
    must chain (answer 2 builds on answer 1's row state)."""
    s, H, C = _random_sparse()
    rng = np.random.default_rng(1)
    # two answers land on class row 2 (the collision), others distinct
    tcs = jnp.asarray([2, 4, 2, 0], jnp.int32)
    preds = jnp.asarray(rng.integers(0, C, (4, H)), jnp.int32)
    fused = jax.jit(lambda st: scatter_rows(st, tcs, preds, 0.01))(s)
    seq = s
    for j in range(4):
        seq = scatter_row(seq, tcs[j], preds[j], 0.01)
    for a, b in zip(jax.tree.leaves(fused), jax.tree.leaves(seq)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_scatter_rows_conserves_row_mass():
    """Each answer adds exactly lr per model to its class row; every
    other row is untouched — mass conservation per row, exact up to
    float addition of the increments themselves."""
    s, H, C = _random_sparse(seed=2)
    rng = np.random.default_rng(3)
    tcs = jnp.asarray([1, 1, 5], jnp.int32)   # same-row collision
    preds = jnp.asarray(rng.integers(0, C, (3, H)), jnp.int32)
    lr = 0.01
    before = _row_masses(s)
    after = _row_masses(scatter_rows(s, tcs, preds, lr))
    expect = before.copy()
    for tc in np.asarray(tcs):
        expect[:, tc] += lr
    np.testing.assert_allclose(after, expect, rtol=2e-6, atol=2e-7)


def test_scatter_rows_parity_layout_matches_dense():
    """K >= C (the parity layout): the fused batch equals the dense
    multi-row scatter-add."""
    rng = np.random.default_rng(4)
    H, C = 4, 5
    dense = jnp.asarray(rng.uniform(0.05, 2.0, (H, C, C)).astype(
        np.float32))
    s = sparsify(dense, C)
    tcs = jnp.asarray([3, 3, 1], jnp.int32)
    preds = jnp.asarray(rng.integers(0, C, (3, H)), jnp.int32)
    lr = 0.01
    out = scatter_rows(s, tcs, preds, lr)
    ref = dense
    for j in range(3):
        onehot = jax.nn.one_hot(preds[j], C, dtype=ref.dtype)
        ref = ref.at[:, tcs[j], :].add(lr * onehot)
    np.testing.assert_allclose(np.asarray(out.vals), np.asarray(ref),
                               rtol=1e-6, atol=1e-7)


def test_coda_sparse_update_q_tracks_dense(task):
    """The fused multi-row update on the sparse:K>=C parity layout stays
    bitwise the dense fused update (same float ops at the same
    positions) over a q-wide trajectory."""
    fac_d = lambda p: make_coda(p, CODAHyperparams())           # noqa
    fac_s = lambda p: make_coda(                                # noqa
        p, CODAHyperparams(posterior="sparse:5"))  # K == C: parity
    rd = run_seeds_compiled(fac_d, task.preds, task.labels, iters=5,
                            seeds=2, acq_batch=4)
    rs = run_seeds_compiled(fac_s, task.preds, task.labels, iters=5,
                            seeds=2, acq_batch=4)
    assert np.array_equal(np.asarray(rd.chosen_idx),
                          np.asarray(rs.chosen_idx))
    assert np.array_equal(np.asarray(rd.best_model),
                          np.asarray(rs.best_model))


# ---------------------------------------------------------------------------
# recorder v2 batch records + replay
# ---------------------------------------------------------------------------

def test_batch_record_roundtrip_and_schema(task, tmp_path):
    from coda_tpu.telemetry.recorder import (
        RECORD_SCHEMA_VERSION,
        RunRecord,
        environment_fingerprint,
    )

    fac = _factories()["coda"]
    res, aux = run_seeds_recorded(fac, task.preds, task.labels, iters=4,
                                  seeds=2, trace_k=4, acq_batch=4)
    rec = RunRecord.from_result(
        res, aux, environment_fingerprint(knobs={"acq_batch": 4}),
        run={"iters": 4, "acq_batch": 4})
    # v2 introduced the q-wide arrays; later bumps (v3: the surrogate
    # fallback stream) keep stamping the current version
    assert rec.meta["schema_version"] == RECORD_SCHEMA_VERSION >= 2
    assert rec.acq_batch == 4
    assert rec.arrays["chosen_idx"].shape == (2, 4, 4)
    rec.save(str(tmp_path / "rec"))
    loaded = RunRecord.load(str(tmp_path / "rec"))
    assert loaded.acq_batch == 4

    # schema checker: clean as written; a q/extent mismatch is flagged
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_record_schema_batchq",
        os.path.join(REPO, "scripts", "check_record_schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check_tree(str(tmp_path)) == {}
    meta = json.loads((tmp_path / "rec" / "record.json").read_text())
    meta["acq_batch"] = 3
    (tmp_path / "rec" / "record.json").write_text(json.dumps(meta))
    bad = mod.check_tree(str(tmp_path))
    assert any("label-batch extent" in v
               for vs in bad.values() for v in vs)


def test_old_record_version_still_loads(task, tmp_path):
    """v1 records (the committed r12 captures' version) load as
    acq_batch=1; an unknown version fails with the real reason."""
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    fac = _factories()["coda"]
    res, aux = run_seeds_recorded(fac, task.preds, task.labels, iters=3,
                                  seeds=1, trace_k=4)
    rec = RunRecord.from_result(res, aux, environment_fingerprint(),
                                run={"iters": 3})
    rec.save(str(tmp_path / "v1"))
    meta = json.loads((tmp_path / "v1" / "record.json").read_text())
    meta["schema_version"] = 1
    del meta["acq_batch"]
    (tmp_path / "v1" / "record.json").write_text(json.dumps(meta))
    loaded = RunRecord.load(str(tmp_path / "v1"))
    assert loaded.acq_batch == 1
    meta["schema_version"] = 99
    (tmp_path / "v1" / "record.json").write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="schema_version"):
        RunRecord.load(str(tmp_path / "v1"))


def test_batch_record_replays_bitwise(task):
    """A q-wide record re-executes the identical q-wide program: same
    backend, same knobs => bitwise parity."""
    from coda_tpu.engine.replay import verify_replay
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    fac = _factories()["coda"]
    res, aux = run_seeds_recorded(fac, task.preds, task.labels, iters=4,
                                  seeds=2, trace_k=4, acq_batch=4)
    rec = RunRecord.from_result(
        res, aux, environment_fingerprint(knobs={"acq_batch": 4}),
        run={"iters": 4, "acq_batch": 4})
    report = verify_replay(rec, fac, task.preds, task.labels,
                           score_tol=0.0)
    assert report.parity, report.to_dict()


def test_compare_records_batchq_envelope_path(task):
    """q=1 vs q>1 records route through the knob-diff envelope triage:
    label-aligned cumulative regret, classification acq-batch-envelope,
    never a crash on the mismatched shapes."""
    from coda_tpu.engine.replay import compare_records, format_triage
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    fac = _factories()["coda"]
    recs = {}
    for q in (1, 4):
        res, aux = run_seeds_recorded(fac, task.preds, task.labels,
                                      iters=12 // q, seeds=2, trace_k=4,
                                      acq_batch=q)
        recs[q] = RunRecord.from_result(
            res, aux, environment_fingerprint(knobs={"acq_batch": q}),
            run={"iters": 12 // q, "acq_batch": q})
    report = compare_records(recs[1], recs[4])
    assert not report.parity
    assert report.meta["knob_diff"]["acq_batch"] == [1, 4]
    env = report.meta["batchq_envelope"]
    assert env["q_a"] == 1 and env["q_b"] == 4
    assert all(s.classification == "acq-batch-envelope"
               for s in report.seeds)
    assert env["seeds"][0]["labels_compared"] == 12
    assert "acq-batch envelope" in format_triage(report)


# ---------------------------------------------------------------------------
# serve: batch labels, idempotency, export/import, version gate
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def batch_app(task):
    from coda_tpu.serve.server import ServeApp
    from coda_tpu.serve.state import SelectorSpec

    app = ServeApp(capacity=4, tiering=False,
                   spec=SelectorSpec.create("coda", acq_batch=4,
                                            n_parallel=4))
    app.add_task(task.name, task.preds)
    app.start(warm=True)
    yield app
    app.drain()


def test_selector_spec_acq_batch_one_is_default():
    from coda_tpu.serve.state import SelectorSpec

    assert SelectorSpec.create("coda") == SelectorSpec.create(
        "coda", acq_batch=1)


def test_batch_label_round_trips(batch_app, task):
    labels = np.asarray(task.labels)
    out = batch_app.open_session()
    assert isinstance(out["idx"], list) and len(out["idx"]) == 4
    sid = out["session"]
    out = batch_app.labels(sid, [int(labels[i]) for i in out["idx"]],
                           idx=out["idx"], request_id="rt0")
    assert out["n_labeled"] == 4
    # a stale idx list is refused; a single-label verb on a q-session too
    from coda_tpu.serve.server import StaleItem

    with pytest.raises(StaleItem):
        batch_app.labels(sid, [0, 0, 0, 0], idx=[0, 1, 2, 3])
    with pytest.raises(ValueError, match="batches 4 labels"):
        batch_app.label(sid, 0)
    with pytest.raises(ValueError, match="exactly 4 labels"):
        batch_app.labels(sid, [0, 0])
    batch_app.close_session(sid)


def test_batch_label_idempotent_under_concurrent_retries(batch_app, task):
    """Concurrent retries sharing (overlapping) request_ids: the q-wide
    answer set commits to the posterior EXACTLY once per request_id."""
    labels = np.asarray(task.labels)
    out = batch_app.open_session()
    sid = out["session"]
    ans = [int(labels[i]) for i in out["idx"]]
    results, errs = [], []

    def hit(rid):
        try:
            results.append(batch_app.labels(sid, ans, request_id=rid))
        except Exception as e:  # pragma: no cover - would fail the test
            errs.append(repr(e))

    # 6 concurrent submissions over TWO overlapping request_ids: each rid
    # must commit exactly once -> exactly 2 rounds = 8 labels... but the
    # second rid races the first commit, so its answers are stale-checked
    # only by rid identity — drive rid "a" concurrently first, then "b"
    threads = [threading.Thread(target=hit, args=("rid-a",))
               for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len({json.dumps(r, sort_keys=True) for r in results}) == 1
    assert results[0]["n_labeled"] == 4
    # the retry AFTER the commit answers from the cache, no re-apply
    again = batch_app.labels(sid, [0, 0, 0, 0], request_id="rid-a")
    assert again["n_labeled"] == 4
    assert again["idx"] == results[0]["idx"]
    batch_app.close_session(sid)


def test_batch_session_export_import_replay(batch_app, task):
    """A q-wide session's stream replays bitwise on import (the carries
    snapshot is stripped to force the row-by-row path, which exercises
    list-valued check_row quantities)."""
    from coda_tpu.serve.server import ServeApp
    from coda_tpu.serve.state import SelectorSpec

    labels = np.asarray(task.labels)
    out = batch_app.open_session()
    sid = out["session"]
    for r in range(3):
        out = batch_app.labels(sid, [int(labels[i]) for i in out["idx"]],
                               request_id=f"e{r}")
    payload = batch_app.export_session(sid)
    assert payload["acq_batch"] == 4
    assert payload["n_labeled"] == 12
    payload = dict(payload, carries=None, key=None)   # force replay path
    app2 = ServeApp(capacity=4, tiering=False,
                    spec=SelectorSpec.create("coda", acq_batch=4,
                                             n_parallel=4))
    app2.add_task(task.name, task.preds)
    app2.start(warm=True)
    try:
        info = app2.import_session(payload)
        assert info["restored_via"] == "replay"
        assert info["n_labeled"] == 12
        assert app2.best(sid)["n_labeled"] == 12
    finally:
        app2.drain()
    batch_app.close_session(sid)


def test_old_session_stream_version_gated(tmp_path, task):
    """The stream version gate: a v2 (pre-batching) stream is STILL
    replayable at acq_batch=1 (v3 only adds fields there — a deploy must
    not discard every in-flight session), a v2 stream cannot restore
    onto a batch server (the real acq_batch reason, not a fake
    divergence), and unknown versions fail with the schema reason."""
    from coda_tpu.serve.recovery import (
        _stream_version_error,
        verify_session_stream,
    )
    from coda_tpu.serve.state import SessionStore

    store = SessionStore(capacity=2)
    store.register_task(task.name, np.asarray(task.preds))
    meta = {"v": 2, "kind": "session_meta", "task": task.name,
            "method": "coda", "seed": 0}
    # v2 at q=1: accepted, empty stream verifies trivially
    assert verify_session_stream(store, meta, [], sid="old")["parity"]
    # unknown versions: the schema gate names the real reason
    with pytest.raises(ValueError, match="stream schema v1"):
        verify_session_stream(store, dict(meta, v=1), [], sid="v1")
    assert _stream_version_error({"v": 5}) is not None
    assert _stream_version_error({"v": 3}) is None
    # a v2 stream restoring onto an acq_batch>1 server: rejected for the
    # acq_batch mismatch (restore_app_sessions path)
    from coda_tpu.serve.server import ServeApp
    from coda_tpu.serve.state import SelectorSpec

    rec_dir = tmp_path / "rec"
    rec_dir.mkdir()
    (rec_dir / "session_deadbeef.jsonl").write_text(
        json.dumps(dict(meta, session="deadbeef")) + "\n")
    app = ServeApp(capacity=2, tiering=False,
                   spec=SelectorSpec.create("coda", acq_batch=4,
                                            n_parallel=2))
    app.add_task(task.name, np.asarray(task.preds))
    app.start(warm=False)
    try:
        report = app.restore_sessions(str(rec_dir))
        assert "acq_batch mismatch" in report["failed"]["deadbeef"]
    finally:
        app.drain()
