"""Tests for the periphery scripts (aggregation, janitor, sweep launcher,
epsilon grid search, format conversion)."""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def seeded_store(tmp_path):
    from coda_tpu.tracking import TrackingStore

    db = str(tmp_path / "db.sqlite")
    store = TrackingStore(db)
    with store.run("taskA", "taskA-coda") as parent:
        for s, (r0, r1) in enumerate([(0.4, 0.2), (0.6, 0.0)]):
            with store.run("taskA", f"taskA-coda-{s}", parent=parent,
                           params={"seed": s, "stochastic": "True"}) as r:
                r.log_metric_series("regret", [r0, r1], start_step=1)
    return store, db


def test_aggregate_results(seeded_store):
    store, db = seeded_store
    agg = _load("aggregate_results")
    n = agg.aggregate_metrics(store, ["regret"], quiet=True)
    assert n == 2
    rows = store.query(
        """SELECT m.step, m.value FROM metrics m
           JOIN tags t ON t.run_uuid = m.run_uuid AND t.key='mlflow.runName'
           WHERE t.value='taskA-coda' AND m.key='mean_regret' ORDER BY m.step"""
    )
    assert rows == [(1, 0.5), (2, 0.1)]


def test_clear_db_selected_and_all(seeded_store, tmp_path):
    store, db = seeded_store
    store.close()
    clear = _load("clear_db")
    clear.delete_selected(db, tasks=["taskA"], methods=None, skip_confirm=True)
    from coda_tpu.tracking import TrackingStore

    store2 = TrackingStore(db)
    assert store2.query("SELECT COUNT(*) FROM runs") == [(0,)]
    assert store2.query("SELECT COUNT(*) FROM metrics") == [(0,)]
    store2.close()
    clear.delete_all(db, skip_confirm=True)
    assert not os.path.exists(db)


def test_convert_pt_roundtrip(tmp_path):
    torch = pytest.importorskip("torch")
    conv = _load("convert_pt")
    p = np.random.default_rng(0).random((3, 8, 4)).astype(np.float32)
    torch.save(torch.from_numpy(p.copy()), str(tmp_path / "t.pt"))
    torch.save(torch.from_numpy(np.arange(8)), str(tmp_path / "t_labels.pt"))
    out = conv.convert(str(tmp_path / "t.pt"))
    out_l = conv.convert(str(tmp_path / "t_labels.pt"))
    np.testing.assert_array_equal(np.load(out), p)
    assert np.load(out_l).dtype == np.int32


def test_launcher_hparam_decode():
    launch = _load("launch_all_methods")
    flags = launch.decode_method_hparams(
        "coda-lr=0.01-mult=2.0-alpha=0.8-q=eig-no-prefilter-no-diag")
    assert flags == ["--learning-rate", "0.01", "--alpha", "0.8",
                     "--multiplier", "2.0", "--q", "eig",
                     "--prefilter-n", "0", "--no-diag-prior"]
    assert launch.decode_method_hparams("iid") == []
    assert launch.decode_method_hparams("coda-prefilter=100") == [
        "--prefilter-n", "100"]


def test_launcher_run_needed(seeded_store):
    store, db = seeded_store
    launch = _load("launch_all_methods")
    # both seeds finished & stochastic -> seeds 0..1 done, seed 2 missing
    assert not launch.run_needed(store, "taskA", "coda", 2)
    assert launch.run_needed(store, "taskA", "coda", 3)
    assert launch.run_needed(store, "taskA", "iid", 1)
    # deterministic finished seed 0 marks the whole run complete
    with store.run("taskB", "taskB-coda") as parent:
        with store.run("taskB", "taskB-coda-0", parent=parent,
                       params={"seed": 0, "stochastic": "False"}):
            pass
    assert not launch.run_needed(store, "taskB", "coda", 5)


def test_launcher_dry_run(tmp_path, capsys):
    launch = _load("launch_all_methods")
    np.save(str(tmp_path / "t1.npy"),
            np.zeros((2, 4, 3), dtype=np.float32))
    np.save(str(tmp_path / "t1_labels.npy"), np.zeros(4, dtype=np.int32))
    rc = launch.main([
        "--pred-dir", str(tmp_path), "--methods", "iid,coda-lr=0.5",
        "--db", str(tmp_path / "db.sqlite"), "--dry-run",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "t1/iid" in out and "t1/coda-lr=0.5" in out
    assert "--learning-rate 0.5" in out


def test_majority_vote_matches_reference_semantics():
    gs = _load("modelselector_eps_gridsearch")
    hard = np.array([[0, 1, 1], [2, 2, 0], [1, 0, 2]], dtype=np.int32)
    # ties broken toward the smallest class id (np.unique order)
    maj = gs.majority_vote_labels(hard, C=3)
    assert maj.tolist() == [1, 2, 0]
    tie = np.array([[0, 1], [2, 1]], dtype=np.int32)
    assert gs.majority_vote_labels(tie, C=3).tolist() == [0, 1]


def test_gridsearch_end_to_end(tmp_path):
    gs = _load("modelselector_eps_gridsearch")
    from coda_tpu.data import make_synthetic_task

    task = make_synthetic_task(seed=2, H=4, N=60, C=3,
                               acc_lo=0.3, acc_hi=0.95)
    res = gs.run_grid_search(
        task.preds, eps_list=[0.4, 0.46], iterations=8, pool_size=30,
        budget=12, seed=0, real_chunk=8)
    assert set(res) == {"best_avg", "best_fast", "metrics"}
    for eps, m in res["metrics"].items():
        assert len(m["success_mean"]) == 12
        assert 0.0 <= m["avg_success"] <= 1.0
        assert all(0.0 <= a <= 1.0 for a in m["acc_mean"])
    # with a clearly-best model the search should find it often by the end
    best_eps = res["best_avg"]
    tail = np.mean(res["metrics"][best_eps]["success_mean"][-4:])
    assert tail > 0.4

    # skip-if-present resume via the results file
    path = str(tmp_path / "best_epsilons.json")
    gs.save_result(path, "taskX", res)
    saved = gs.load_results(path)
    assert saved["taskX"]["best_avg"] == res["best_avg"]


def test_launcher_srun_path_executes_fake_launcher(tmp_path):
    """L7 cluster path: --launcher must PREFIX every job command and actually
    be exec'd (reference ``scripts/launch_all_methods.py:135-153`` hard-codes
    srun; here the prefix is generic). A fake launcher binary records its
    argv instead of running the job, proving the composed command line and
    the pool's completion handling without a cluster."""
    launch = _load("launch_all_methods")
    np.save(str(tmp_path / "t1.npy"), np.zeros((2, 4, 3), dtype=np.float32))
    np.save(str(tmp_path / "t1_labels.npy"), np.zeros(4, dtype=np.int32))
    log = tmp_path / "launches.log"
    fake = tmp_path / "fake_srun"
    fake.write_text(
        "#!/bin/sh\n"
        f'echo "$@" >> "{log}"\n'
    )
    fake.chmod(0o755)

    rc = launch.main([
        "--pred-dir", str(tmp_path), "--methods", "iid,coda-lr=0.5",
        "--db", str(tmp_path / "db.sqlite"),
        "--launcher", f"{fake} -p tpu-part --mem=64GB",
        "--polling-interval", "0.05",
    ])
    assert rc == 0
    lines = log.read_text().strip().splitlines()
    assert len(lines) == 2  # one launcher exec per task-method job
    for line in lines:
        # launcher args come first, then the job command
        assert line.startswith("-p tpu-part --mem=64GB ")
        assert "main.py" in line and "--task t1" in line
        assert f"--data-dir {tmp_path}" in line
    assert any("--method iid" in l for l in lines)
    assert any("--method coda-lr=0.5" in l and "--learning-rate 0.5" in l
               for l in lines)


def test_launcher_resume_skips_finished_jobs(tmp_path, capsys):
    """DB-checked resume through the real entry point: a task-method whose
    seeds are all FINISHED is skipped; unfinished ones still launch."""
    from coda_tpu.tracking import TrackingStore

    launch = _load("launch_all_methods")
    np.save(str(tmp_path / "t1.npy"), np.zeros((2, 4, 3), dtype=np.float32))
    np.save(str(tmp_path / "t1_labels.npy"), np.zeros(4, dtype=np.int32))
    db = str(tmp_path / "db.sqlite")
    store = TrackingStore(db)
    with store.run("t1", "t1-iid") as parent:
        with store.run("t1", "t1-iid-0", parent=parent,
                       params={"seed": 0, "stochastic": "False"}):
            pass  # deterministic seed 0 -> whole method complete
    store.close()

    log = tmp_path / "launches.log"
    fake = tmp_path / "fake_srun"
    fake.write_text(f'#!/bin/sh\necho "$@" >> "{log}"\n')
    fake.chmod(0o755)
    rc = launch.main([
        "--pred-dir", str(tmp_path), "--methods", "iid,vma",
        "--db", db, "--launcher", str(fake),
        "--polling-interval", "0.05", "--seeds", "3",
    ])
    assert rc == 0
    assert "Skipping t1/iid" in capsys.readouterr().out
    lines = log.read_text().strip().splitlines()
    assert len(lines) == 1 and "--method vma" in lines[0]
