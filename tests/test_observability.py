"""Observability tests: distributed tracing + the SLO watchtower
(``telemetry/trace.py`` / ``telemetry/spans.py`` / ``telemetry/slo.py``
and their serve-layer plumbing).

The load-bearing claims: (1) burn-rate alerting follows the multi-window
state machine — fire only when BOTH windows burn, clear on fast-window
hysteresis, never before ``min_samples`` — against an injected clock, no
sleeping; (2) tracing is purely observational: the same seeded session
with tracing on vs off yields BITWISE-identical decision rows (trace_id
is additive-optional); (3) one trace that crosses a forced mid-session
migration stitches into one file holding the router's AND both replicas'
process lanes, and a rolling-restarted replica's spans survive via the
router's span adoption; (4) /metrics latency exemplars are joinable —
their trace_id fetches retained spans — and the exemplar syntax is
lint-legal exactly on gauge/histogram families; (5) the HTTP fleet front
door serves /metrics (per-replica-labeled families + slo_*), /fleet/slo
and /trace/id/{id} over real HTTP against subprocess replicas.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

H, N, C = 4, 48, 4
_ROW_KEYS = ("next_idx", "next_prob", "best", "pbest_max", "pbest_entropy")


@pytest.fixture(scope="module")
def task():
    from coda_tpu.data import make_synthetic_task

    return make_synthetic_task(seed=0, H=H, N=N, C=C)


def _app(task, tracing=True, **kw):
    from coda_tpu.serve import SelectorSpec, ServeApp

    app = ServeApp(capacity=4, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=4),
                   tracing=tracing, **kw)
    app.add_task(task.name, task.preds)
    return app


def _fleet(task, n=2, warm=False):
    from coda_tpu.serve import Fleet

    def make(rid):
        return _app(task)

    return Fleet(make, n_replicas=n).start(warm=warm)


# ---------------------------------------------------------------------------
# burn-rate window math (injected clock — no sleeping)
# ---------------------------------------------------------------------------

def _sweeper(**kw):
    from coda_tpu.telemetry.slo import SLObjective, SloSweeper

    obj = SLObjective("unit", "synthetic bad fraction",
                      lambda snap: snap.get("bad"), budget=0.05)
    defaults = dict(fast_s=10.0, slow_s=60.0, min_samples=3,
                    clock=lambda: 0.0)
    defaults.update(kw)
    return SloSweeper([obj], **defaults)


def test_burn_rate_fires_only_when_both_windows_burn():
    sw = _sweeper()
    # two bad samples: under min_samples, must NOT fire
    assert sw.observe({"bad": 1.0}, t=0.0) == []
    assert sw.observe({"bad": 1.0}, t=1.0) == []
    st = sw.snapshot()["objectives"]["unit"]
    assert not st["firing"] and st["burn_fast"] == pytest.approx(20.0)
    # third sample crosses min_samples with both windows at 20x: fires
    evs = sw.observe({"bad": 1.0}, t=2.0)
    assert [e["state"] for e in evs] == ["firing"]
    st = sw.snapshot()["objectives"]["unit"]
    assert st["firing"] and st["fired_total"] == 1
    # refire is not a new alert while still firing
    assert sw.observe({"bad": 1.0}, t=3.0) == []


def test_burn_rate_slow_window_vetoes_a_fast_blip():
    # a long good history keeps the SLOW window cold: a fast burst alone
    # must not page (the multi-window point)
    sw = _sweeper()
    for i in range(55):
        assert sw.observe({"bad": 0.0}, t=float(i)) == []
    for t in (55.0, 56.0, 57.0, 58.0, 59.0):
        assert sw.observe({"bad": 1.0}, t=t) == []
    st = sw.snapshot()["objectives"]["unit"]
    assert st["burn_fast"] >= sw.fire_threshold   # fast IS burning
    assert st["burn_slow"] < sw.fire_threshold    # slow veto
    assert not st["firing"]


def test_burn_rate_clear_hysteresis():
    sw = _sweeper()
    for t in (0.0, 1.0, 2.0):
        sw.observe({"bad": 1.0}, t=t)
    assert sw.snapshot()["objectives"]["unit"]["firing"]
    # good samples, but the bad ones are still inside the fast window:
    # burn stays >= clear_threshold, the alert must NOT flap
    evs = []
    for t in (3.0, 4.0, 5.0):
        evs += sw.observe({"bad": 0.0}, t=t)
    assert evs == []
    assert sw.snapshot()["objectives"]["unit"]["firing"]
    # once the window slides past the bad burst, fast burn -> 0: resolve
    evs = sw.observe({"bad": 0.0}, t=13.5)
    assert [e["state"] for e in evs] == ["resolved"]
    st = sw.snapshot()["objectives"]["unit"]
    assert not st["firing"] and st["cleared_total"] == 1
    assert [a["state"] for a in sw.snapshot()["alerts"]] == \
        ["firing", "resolved"]


def test_burn_rate_no_data_probe_never_burns():
    sw = _sweeper()
    for t in (0.0, 1.0, 2.0, 3.0):
        assert sw.observe({}, t=t) == []      # probe returns None
    st = sw.snapshot()["objectives"]["unit"]
    assert st["no_data"] and not st["firing"]
    assert st["window_samples"] == [0, 0]


def test_slo_alerts_flush_via_store_factory_from_worker_thread(tmp_path):
    """The store may be a zero-arg factory, resolved lazily on whichever
    thread flushes first — the sqlite thread-affinity contract."""
    from coda_tpu.tracking.store import TrackingStore

    db = str(tmp_path / "slo.sqlite")
    sw = _sweeper(store=lambda: TrackingStore(db))

    def drive():
        for t in (0.0, 1.0, 2.0):
            sw.observe({"bad": 1.0}, t=t)
        for t in (11.0, 12.0, 13.0):
            sw.observe({"bad": 0.0}, t=t)

    th = threading.Thread(target=drive)
    th.start()
    th.join()
    snap = sw.snapshot()
    assert snap["store"] == {"flushed": 2, "errors": 0}
    store = TrackingStore(db)
    try:
        assert store.is_finished("serve_slo", "alert-unit-firing")
        assert store.is_finished("serve_slo", "alert-unit-resolved")
    finally:
        store.close()


def test_default_fleet_slos_probe_router_snapshot(task):
    """The shipped objective set evaluates a real fleet snapshot without
    error, and the label_p99 probe flags a p99 beyond its bound."""
    from coda_tpu.telemetry.slo import default_fleet_slos

    objs = {o.name: o for o in default_fleet_slos(label_p99_ms=250.0)}
    fleet = _fleet(task)
    try:
        out = fleet.router.open_session(seed=0)
        for _ in range(2):
            out = fleet.router.label(out["session"], int(out["idx"]) % C)
        snap = fleet.stats()
        vals = {name: o.probe(snap) for name, o in objs.items()}
        assert vals["label_p99"] in (0.0, 1.0)
        assert vals["error_ratio"] == 0.0
        # the bound is a knob: an absurdly tight one must read as bad
        tight = {o.name: o
                 for o in default_fleet_slos(label_p99_ms=1e-6)}
        assert tight["label_p99"].probe(snap) == 1.0
    finally:
        fleet.drain()


# ---------------------------------------------------------------------------
# tracing: non-perturbation + cross-process stitching
# ---------------------------------------------------------------------------

def _run_session(app, n_labels, traced):
    from coda_tpu.telemetry.trace import mint

    out = app.open_session(seed=5)
    sid = out["session"]
    for _ in range(n_labels):
        ctx = mint() if traced else None
        out = app.label(sid, int(out["idx"]) % C, trace_ctx=ctx)
    return sid


def test_tracing_on_vs_off_bitwise_rows(task):
    on, off = _app(task, tracing=True), _app(task, tracing=False)
    on.start(warm=False)
    off.start(warm=False)
    try:
        sid_on = _run_session(on, 6, traced=True)
        sid_off = _run_session(off, 6, traced=False)
        rows_on = on.recorder.history(sid_on)
        rows_off = off.recorder.history(sid_off)
        assert len(rows_on) == len(rows_off) == 7
        for a, b in zip(rows_on, rows_off):
            for k in _ROW_KEYS:
                va, vb = a[k], b[k]
                if isinstance(va, float):
                    assert np.float32(va).tobytes() == \
                        np.float32(vb).tobytes(), (k, va, vb)
                else:
                    assert va == vb, (k, va, vb)
        # the join is additive-optional: present on traced LABEL rows,
        # absent (not null) everywhere in the untraced stream
        assert all(r.get("trace_id") for r in rows_on if r["do_update"])
        assert all("trace_id" not in r for r in rows_off)
    finally:
        on.drain(timeout=10)
        off.drain(timeout=10)


def test_trace_spans_forced_migration_across_both_lanes(task):
    from coda_tpu.telemetry.trace import mint

    fleet = _fleet(task)
    try:
        router = fleet.router
        out = router.open_session(seed=3)
        sid = out["session"]
        src = router.owner_of(sid)
        ctx = mint()
        out = router.label(sid, int(out["idx"]) % C, trace_ctx=ctx)
        dst = next(r for r in fleet.replica_ids if r != src)
        info = router.migrate_session(sid, src, dst)
        assert info.get("migrated") == sid
        router.label(sid, int(out["idx"]) % C, trace_ctx=ctx)
        stitched = router.collect_trace(ctx.trace_id)
        assert stitched["trace_id"] == ctx.trace_id
        assert set(stitched["processes"]) >= {"router", src, dst}
        names = [e["name"] for e in stitched["traceEvents"]
                 if e.get("ph") == "X"]
        for prefix in ("route/", "dispatch/", "serve/", "tick/"):
            assert any(n.startswith(prefix) for n in names), (prefix,
                                                              names)
    finally:
        fleet.drain()


def test_restart_adopts_spans_so_traces_survive(task):
    """restart_replica rebuilds the app (fresh SpanRecorder) — the
    router must adopt the dying replica's retained spans so the trace
    keeps that replica's lane afterwards."""
    from coda_tpu.telemetry.trace import mint

    fleet = _fleet(task)
    try:
        router = fleet.router
        out = router.open_session(seed=1)
        sid = out["session"]
        rid = router.owner_of(sid)
        ctx = mint()
        router.label(sid, int(out["idx"]) % C, trace_ctx=ctx)
        before = set(router.collect_trace(ctx.trace_id)["processes"])
        assert rid in before
        fleet.restart_replica(rid, warm=False)
        after = router.collect_trace(ctx.trace_id)
        assert rid in after["processes"], after["processes"]
        # adoption + the live (empty) post-restart recorder must not
        # duplicate the lane
        assert after["processes"].count(rid) == 1
    finally:
        fleet.drain()


# ---------------------------------------------------------------------------
# exemplars: /metrics -> trace join + lint legality
# ---------------------------------------------------------------------------

def test_latency_exemplars_join_to_retained_spans(task):
    from coda_tpu.telemetry.prometheus import lint, render

    app = _app(task, tracing=True)
    app.start(warm=False)
    try:
        _run_session(app, 6, traced=True)
        exemplars = {ring: ex
                     for ring, ex in (app.metrics.snapshot()
                                      .get("exemplars") or {}).items()
                     if ex and ex.get("trace_id")}
        assert "request_latency" in exemplars
        for ex in exemplars.values():
            payload = app.trace_by_id(ex["trace_id"])
            assert payload["events"], ex   # the join lands on real spans
        text = render(registry=app.telemetry.registry,
                      serve_metrics=app.metrics)
        assert " # {trace_id=\"" in text
        assert lint(text) == []
    finally:
        app.drain(timeout=10)


def test_lint_exemplar_rules():
    from coda_tpu.telemetry.prometheus import lint

    good = ('# TYPE g gauge\n'
            'g{ring="request_latency"} 0.25 # {trace_id="abc"} 0.25\n')
    assert lint(good) == []
    on_counter = ('# TYPE c counter\n'
                  'c_total 3 # {trace_id="abc"} 3\n')
    assert any("only legal on" in v for v in lint(on_counter))
    malformed = ('# TYPE g gauge\n'
                 'g 0.25 # {trace_id=abc} 0.25\n')
    assert any("malformed exemplar labels" in v for v in lint(malformed))


# ---------------------------------------------------------------------------
# the HTTP fleet front door (subprocess replicas — satellite: the
# HTTP-fleet metrics gap)
# ---------------------------------------------------------------------------

def test_http_fleet_metrics_slo_and_trace_endpoints(task):
    import os
    import re
    import subprocess
    import sys
    import time as _time
    import urllib.request

    from coda_tpu.serve import HttpReplica, SessionRouter, make_server
    from coda_tpu.telemetry.prometheus import lint
    from coda_tpu.telemetry.trace import TRACE_HEADER, mint

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs, urls = [], {}
    router = None
    try:
        for rid in ("h0", "h1"):
            p = subprocess.Popen(
                [sys.executable, "-u", "-m", "coda_tpu.cli", "serve",
                 "--synthetic", f"{H},{N},{C}", "--port", "0",
                 "--capacity", "4", "--no-warm"],
                cwd=repo, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            procs.append(p)
            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline:
                line = p.stdout.readline()
                m = re.search(r"http://127\.0\.0\.1:(\d+)/", line or "")
                if m:
                    urls[rid] = f"http://127.0.0.1:{m.group(1)}"
                    break
                if p.poll() is not None:
                    raise RuntimeError(f"replica {rid} died at startup")
            assert rid in urls, "replica never announced its port"
        for url in urls.values():
            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(url + "/healthz",
                                                timeout=2):
                        break
                except Exception:
                    _time.sleep(0.2)
        router = SessionRouter({rid: HttpReplica(rid, url)
                                for rid, url in urls.items()},
                               slo_fast_s=5.0, slo_slow_s=30.0)
        srv = make_server(router, 0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"

        def req(method, path, body=None, headers=None):
            data = None if body is None else json.dumps(body).encode()
            rq = urllib.request.Request(base + path, data=data,
                                        method=method,
                                        headers=headers or {})
            with urllib.request.urlopen(rq, timeout=60) as resp:
                return resp.status, resp.read()

        ctx = mint()
        code, body = req("POST", "/session", {"seed": 2},
                         headers={TRACE_HEADER: ctx.header()})
        out = json.loads(body)
        sid = out["session"]
        code, body = req("POST", f"/session/{sid}/label",
                         {"label": int(out["idx"]) % C},
                         headers={TRACE_HEADER: ctx.header()})
        assert code == 200 and json.loads(body)["n_labeled"] == 1

        # /metrics: per-replica-labeled serve families over real HTTP,
        # plus the slo_* families once the sweeper has observed, and the
        # whole exposition lint-clean
        router.slo.observe(router.stats())
        code, body = req("GET", "/metrics")
        text = body.decode()
        assert code == 200
        assert re.search(r'coda_serve_requests_total\{replica="h0"\} ',
                         text)
        assert re.search(r'coda_serve_requests_total\{replica="h1"\} ',
                         text)
        assert 'coda_slo_firing{slo="label_p99"}' in text
        assert lint(text) == []

        # /fleet/slo: the watchtower's JSON face at the front door
        code, body = req("GET", "/fleet/slo")
        slo = json.loads(body)
        assert code == 200
        assert set(slo["objectives"]) >= {"label_p99", "error_ratio"}
        assert slo["windows_s"] == {"fast": 5.0, "slow": 30.0}

        # /trace/id/{id}: the stitched cross-process trace — the
        # router's lane plus the serving replica's, fetched over the
        # same HTTP transport the verbs ride
        code, body = req("GET", f"/trace/id/{ctx.trace_id}")
        stitched = json.loads(body)
        assert code == 200 and stitched["trace_id"] == ctx.trace_id
        procs_seen = set(stitched["processes"])
        assert "router" in procs_seen
        assert procs_seen & {"h0", "h1"}, stitched["processes"]
        names = [e["name"] for e in stitched["traceEvents"]
                 if e.get("ph") == "X"]
        assert any(n.startswith("route/") for n in names)
        assert any(n.startswith("serve/") for n in names)
    finally:
        if router is not None:
            router.drain()
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=10)
