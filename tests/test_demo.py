"""Tests for the human-in-the-loop demo server and the zero-shot pool builder.

The demo is exercised end-to-end over real HTTP (stdlib client against a
server on an ephemeral port) with a tiny synthetic pool; the pool builder is
exercised offline with injected fake scorers — SURVEY.md §4's fixture-based
strategy applied to the periphery the reference never tested.
"""

from __future__ import annotations

import http.client
import json
import os
import threading

import numpy as np
import pytest


@pytest.fixture(scope="module")
def demo_server():
    from coda_tpu.data import make_synthetic_task
    from demo.app import DemoSession, make_server

    task = make_synthetic_task(seed=0, H=3, N=30, C=4)

    def factory():
        return DemoSession(task.preds, task.labels, seed=0)

    srv = make_server(factory, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1]
    srv.shutdown()


def _req(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path, body=json.dumps(body) if body else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_demo_page_served(demo_server):
    status, body = _req(demo_server, "GET", "/")
    assert status == 200
    assert b"CODA" in body


def test_demo_full_loop(demo_server):
    status, body = _req(demo_server, "POST", "/api/start", {})
    assert status == 200
    out = json.loads(body)
    token, state = out["token"], out["state"]
    assert state["idx"] is not None
    assert len(state["pbest"]) == 3
    np.testing.assert_allclose(sum(state["pbest"]), 1.0, atol=1e-5)

    # honest oracle for 3 rounds: answer with the true label
    for _ in range(3):
        status, body = _req(demo_server, "POST", "/api/answer",
                            {"token": token, "label": state["true_label"]})
        assert status == 200
        state = json.loads(body)
    assert state["n_labeled"] == 3

    # "I don't know" removes the point without a belief update
    # (reference demo/app.py:186-189)
    idx_before = state["idx"]
    status, body = _req(demo_server, "POST", "/api/answer",
                        {"token": token, "label": "skip"})
    state = json.loads(body)
    assert state["n_skipped"] == 1
    assert state["n_labeled"] == 3
    assert state["idx"] != idx_before  # the skipped point left the pool


def test_demo_unknown_session(demo_server):
    status, _ = _req(demo_server, "POST", "/api/answer",
                     {"token": "nope", "label": 0})
    assert status == 400


def test_demo_no_images_fallback(demo_server):
    """Tensor-only sessions report has_images=False and 404 the image
    route (the prediction-table fallback)."""
    status, body = _req(demo_server, "POST", "/api/start", {})
    out = json.loads(body)
    assert out["state"]["has_images"] is False
    status, _ = _req(demo_server, "GET",
                     f"/api/image?token={out['token']}&idx=0")
    assert status == 404


# ---------------------------------------------------------------------------
# image-backed demo: the page shows the item being labeled
# ---------------------------------------------------------------------------

# 1x1 red PNG (valid image bytes for the content-type contract)
_PNG = bytes.fromhex(
    "89504e470d0a1a0a0000000d49484452000000010000000108020000009077"
    "53de0000000c4944415408d763f8cfc000000301010018dd8db00000000049"
    "454e44ae426082"
)


@pytest.fixture(scope="module")
def image_demo_server(tmp_path_factory):
    from coda_tpu.data import make_synthetic_task
    from demo.app import DemoSession, make_server

    d = tmp_path_factory.mktemp("demo_imgs")
    N = 20
    paths = []
    for i in range(N):
        p = d / f"img_{i:02d}.png"
        p.write_bytes(_PNG)
        paths.append(str(p))
    task = make_synthetic_task(seed=1, H=3, N=N, C=4)

    def factory():
        return DemoSession(task.preds, task.labels, seed=0,
                           image_paths=paths)

    srv = make_server(factory, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1]
    srv.shutdown()


def test_demo_serves_item_image(image_demo_server):
    """The reference demo loop end to end: the session proposes an item,
    the image route returns its actual bytes, a label advances the loop
    (reference demo/app.py:137-210)."""
    status, body = _req(image_demo_server, "POST", "/api/start", {})
    assert status == 200
    out = json.loads(body)
    token, state = out["token"], out["state"]
    assert state["has_images"] is True
    assert state["idx"] is not None

    conn = http.client.HTTPConnection("127.0.0.1", image_demo_server,
                                      timeout=30)
    conn.request("GET", f"/api/image?token={token}&idx={state['idx']}")
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "image/png"
    assert data == _PNG

    # label it; the next item's image is also servable
    status, body = _req(image_demo_server, "POST", "/api/answer",
                        {"token": token, "label": state["true_label"]})
    state = json.loads(body)
    assert state["n_labeled"] == 1
    status, _ = _req(image_demo_server, "GET",
                     f"/api/image?token={token}&idx={state['idx']}")
    assert status == 200


def test_demo_image_route_validates(image_demo_server):
    status, body = _req(image_demo_server, "POST", "/api/start", {})
    token = json.loads(body)["token"]
    status, _ = _req(image_demo_server, "GET",
                     f"/api/image?token={token}&idx=9999")
    assert status == 400
    status, _ = _req(image_demo_server, "GET",
                     f"/api/image?token={token}&idx=abc")
    assert status == 400
    status, _ = _req(image_demo_server, "GET", "/api/image?token=nope&idx=0")
    assert status == 404


def test_demo_session_rejects_mismatched_paths():
    from coda_tpu.data import make_synthetic_task
    from demo.app import DemoSession

    task = make_synthetic_task(seed=2, H=3, N=10, C=3)
    with pytest.raises(ValueError, match="image paths"):
        DemoSession(task.preds, task.labels, image_paths=["only_one.png"])


# ---------------------------------------------------------------------------
# pool builder
# ---------------------------------------------------------------------------

@pytest.fixture()
def image_dir(tmp_path):
    d = tmp_path / "imgs"
    d.mkdir()
    for i in range(6):
        (d / f"img_{i:02d}.png").write_bytes(b"\x89PNG fake")
    return str(d)


def _fake_scorer(bias_class, n_classes, fail_on=None):
    def score(image_path, classes):
        assert len(classes) == n_classes
        if fail_on and os.path.basename(image_path) == fail_on:
            raise RuntimeError("deliberate failure")
        p = np.full(n_classes, 0.1)
        p[bias_class] = 1.0
        return (p / p.sum()).tolist()

    return score


def test_build_pool_offline(image_dir, tmp_path):
    from demo.hf_zeroshot import build_pool

    classes = ["a", "b", "c"]
    out = str(tmp_path / "pool")
    preds = build_pool(
        image_dir, classes, out,
        models=["fake/m0", "fake/m1"],
        scorers={"fake/m0": _fake_scorer(0, 3),
                 "fake/m1": _fake_scorer(1, 3, fail_on="img_03.png")},
        labels=[0, 1, 2, 0, 1, 2],
    )
    assert preds.shape == (2, 6, 3)
    # model 0 biased to class a everywhere
    assert (preds[0].argmax(-1) == 0).all()
    # the failed image degraded to uniform (reference fallback semantics)
    np.testing.assert_allclose(preds[1, 3], 1.0 / 3, atol=1e-6)

    # the saved npz round-trips through the framework Dataset, including
    # the recorded item filenames + class names (what the demo's image
    # serving keys on)
    from coda_tpu.data import Dataset

    ds = Dataset.from_file(out + ".npz")
    assert ds.preds.shape == (2, 6, 3)
    assert ds.labels is not None
    assert ds.filenames == [f"img_{i:02d}.png" for i in range(6)]
    assert ds.class_names == classes

    # filenames + --images-dir resolve to the actual on-disk paths
    from demo.app import resolve_image_paths

    paths = resolve_image_paths(ds, image_dir)
    assert len(paths) == 6
    assert all(os.path.exists(p) for p in paths)
    assert resolve_image_paths(ds, None) is None


def test_build_pool_resume_skips_existing(image_dir, tmp_path):
    from demo.hf_zeroshot import build_pool

    classes = ["a", "b"]
    out = str(tmp_path / "pool")
    calls = {"n": 0}

    def counting(image_path, classes):
        calls["n"] += 1
        return [0.5, 0.5]

    build_pool(image_dir, classes, out, models=["fake/m"],
               scorers={"fake/m": counting})
    first = calls["n"]
    assert first == 6
    # second run: resume skips the model entirely (skip-if-exists)
    build_pool(image_dir, classes, out, models=["fake/m"],
               scorers={"fake/m": counting})
    assert calls["n"] == first


def test_bioclip_scorer_wiring_with_stub(image_dir, tmp_path, monkeypatch):
    """The pybioclip branch (previously zero-coverage — the library is
    absent in this image, so the import gate always skipped it): inject a
    fake ``bioclip`` module and drive the REAL ``_bioclip_scorer`` wiring
    through ``build_pool`` — name-based backend inference, the
    one-classifier-per-class-list cache, the predict -> by-label score
    mapping, and assembly into the (H, N, C) tensor."""
    import sys
    import types

    calls = {"init": 0, "predict": 0}

    class FakeClassifier:
        def __init__(self, classes):
            calls["init"] += 1
            self.classes = list(classes)

        def predict(self, image_path):
            calls["predict"] += 1
            assert os.path.exists(image_path)
            # pybioclip's record schema: one dict per class
            return [{"classification": c, "score": float(i + 1)}
                    for i, c in enumerate(self.classes)]

    mod = types.ModuleType("bioclip")
    mod.CustomLabelsClassifier = FakeClassifier
    monkeypatch.setitem(sys.modules, "bioclip", mod)

    from demo.hf_zeroshot import build_pool, make_scorer

    # backend inference: a name containing 'bioclip' routes to the branch
    scorer = make_scorer("imageomics/bioclip")
    assert scorer(os.path.join(image_dir, "img_00.png"),
                  ["x", "y"]) == [1.0, 2.0]

    classes = ["a", "b", "c"]
    preds = build_pool(image_dir, classes, str(tmp_path / "pool"),
                       models=["imageomics/bioclip"])
    assert preds.shape == (1, 6, 3)
    # by-label mapping preserved the per-class scores for every image
    np.testing.assert_allclose(preds[0], np.tile([1.0, 2.0, 3.0], (6, 1)))
    # ONE classifier instance per class list, not per image (the cache);
    # the make_scorer smoke call above built its own for ["x", "y"]
    assert calls["init"] == 2
    assert calls["predict"] == 1 + 6


def test_build_pool_unavailable_backend_is_gated(image_dir, tmp_path):
    """A model whose library is missing is skipped, not fatal."""
    from demo import hf_zeroshot
    from demo.hf_zeroshot import build_pool

    def raising_factory(name):
        raise ImportError("no such backend")

    orig = hf_zeroshot.make_scorer
    hf_zeroshot.make_scorer = raising_factory
    try:
        with pytest.raises(RuntimeError, match="no model backend"):
            build_pool(image_dir, ["a", "b"], str(tmp_path / "p"),
                       models=["gone/model"])
    finally:
        hf_zeroshot.make_scorer = orig


# ---------------------------------------------------------------------------
# the REAL transformers path, using the committed locally-trained checkpoint
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TINY_CLIP = os.path.join(REPO, "demo", "models", "tiny-clip-a")


@pytest.mark.skipif(
    not os.path.exists(os.path.join(_TINY_CLIP, "model.safetensors"))
    or not os.path.exists(
        os.path.join(REPO, "demo", "digit_images", "labels.npy")),
    reason="committed tiny-clip checkpoint or digit images not present",
)
def test_hf_pipeline_scorer_real_checkpoint():
    """`make_scorer` -> `_hf_pipeline_scorer` -> transformers pipeline on the
    COMMITTED locally-trained CLIP checkpoint (scripts/train_tiny_clip.py):
    the exact code path the reference runs against hub checkpoints
    (reference ``demo/hf_zeroshot.py:170-219``), with no injected fakes. The
    committed pool data/digits_clip.npz was produced by this same path."""
    pytest.importorskip("transformers")
    from demo.hf_zeroshot import make_scorer

    img_dir = os.path.join(REPO, "demo", "digit_images")
    imgs = sorted(f for f in os.listdir(img_dir) if f.endswith(".png"))[:4]
    labels = np.load(os.path.join(img_dir, "labels.npy"))

    scorer = make_scorer(_TINY_CLIP)
    classes = [str(d) for d in range(10)]
    hits = 0
    for name in imgs:
        scores = scorer(os.path.join(img_dir, name), classes)
        assert len(scores) == 10
        assert abs(sum(scores) - 1.0) < 1e-6
        n = int(name[len("digit_"):-len(".png")])
        hits += int(int(np.argmax(scores)) == int(labels[n]))
    # tiny-clip-a is 90.5% accurate on this split; 4 images are a smoke
    # check, not a statistical claim — require it beats guessing overall
    assert hits >= 2


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REPO, "data", "digits_clip.npz")),
    reason="committed CLIP pool not present",
)
def test_committed_clip_pool_loads_as_dataset():
    """The committed real-model pool is a first-class task: loads through
    Dataset.from_file with labels, filenames and class names intact."""
    from coda_tpu.data import Dataset

    ds = Dataset.from_file(os.path.join(REPO, "data", "digits_clip.npz"))
    H, N, C = ds.preds.shape
    assert (H, N, C) == (3, 899, 10)
    assert ds.labels is not None and ds.labels.shape == (N,)
    assert ds.class_names == [str(d) for d in range(10)]
    assert ds.filenames[0] == "digit_0000.png"
    accs = (np.asarray(ds.preds).argmax(-1) ==
            np.asarray(ds.labels)[None]).mean(-1)
    # the three committed checkpoints' zero-shot accuracies (train_meta.json)
    np.testing.assert_allclose(accs, [0.9055, 0.8687, 0.4983], atol=2e-3)


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REPO, "data", "digits_clip.npz"))
    or not os.path.exists(
        os.path.join(REPO, "demo", "digit_images", "labels.npy")),
    reason="committed CLIP pool or digit images not present",
)
def test_demo_serves_real_clip_pool_end_to_end():
    """The full reference demo experience on REAL artifacts: the committed
    CLIP pool + the committed digit scans through the HTTP server — start a
    session, fetch the actual PNG being labeled, answer honestly, watch
    P(best) move. The reference's demo wires exactly this (iWildCam images
    + a 3-model pool, reference demo/app.py:137-210)."""
    from coda_tpu.data import Dataset
    from demo.app import DemoSession, make_server, resolve_image_paths

    ds = Dataset.from_file(os.path.join(REPO, "data", "digits_clip.npz"))
    paths = resolve_image_paths(
        ds, os.path.join(REPO, "demo", "digit_images"))

    def factory():
        return DemoSession(ds.preds, ds.labels,
                           class_names=ds.class_names,
                           image_paths=paths, seed=0)

    srv = make_server(factory, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        port = srv.server_address[1]
        status, body = _req(port, "POST", "/api/start", {})
        assert status == 200
        out = json.loads(body)
        token, state = out["token"], out["state"]
        assert state["has_images"]
        assert len(state["pbest"]) == 3

        # the served image must be the REAL committed PNG for that item
        status, img = _req(
            port, "GET", f"/api/image?token={token}&idx={state['idx']}")
        assert status == 200
        with open(paths[state["idx"]], "rb") as f:
            assert img == f.read()

        # answer honestly for 5 rounds; P(best) should concentrate on the
        # strongest checkpoint (tiny-clip-a, model 0: 90.5% vs 86.9/49.8)
        for _ in range(5):
            status, body = _req(port, "POST", "/api/answer",
                                {"token": token,
                                 "label": state["true_label"]})
            assert status == 200
            state = json.loads(body)
        assert state["n_labeled"] == 5
        assert int(np.argmax(state["pbest"])) == 0
    finally:
        srv.shutdown()
        srv.server_close()


@pytest.mark.skipif(
    not os.path.exists(os.path.join(_TINY_CLIP, "model.safetensors"))
    or not os.path.exists(
        os.path.join(REPO, "demo", "digit_images", "labels.npy")),
    reason="committed tiny-clip checkpoint or digit images not present",
)
def test_manual_processor_scorer_real_checkpoint():
    """The NON-pipeline backend (manual processor -> model -> softmax, the
    reference's SigLIP branch ``demo/hf_zeroshot.py:118-168``) runs the
    committed locally-trained CLIP checkpoint end-to-end and agrees with
    the pipeline backend on the same images (same checkpoint, same
    hypothesis template — the two paths must rank alike)."""
    pytest.importorskip("transformers")
    from demo.hf_zeroshot import make_scorer

    img_dir = os.path.join(REPO, "demo", "digit_images")
    imgs = sorted(f for f in os.listdir(img_dir) if f.endswith(".png"))[:3]
    classes = [str(d) for d in range(10)]
    manual = make_scorer(_TINY_CLIP, backend="manual")
    pipe = make_scorer(_TINY_CLIP, backend="pipeline")
    for name in imgs:
        p = os.path.join(img_dir, name)
        s_m = manual(p, classes)
        s_p = pipe(p, classes)
        assert len(s_m) == 10 and abs(sum(s_m) - 1.0) < 1e-5
        assert int(np.argmax(s_m)) == int(np.argmax(s_p)), name
