"""Tiered posterior state tests (``coda_tpu/serve/tiering.py``).

The load-bearing claims: (1) a session paged out to the warm or cold tier
and woken by a later label/best/trace is BITWISE the session that never
left the slab — trajectory rows and recorder streams both; (2) demotion
cleanly LOSES every race against live traffic — an in-flight label
ticket or a concurrent export pins the session and the demotion aborts
with state untouched, never a lost or double-applied label; (3) admission
past slab capacity demotes the coldest session instead of answering 503,
so open sessions are bounded by RAM+disk, not slab slots; (4) crash
restore holds across tiers — beyond-capacity record dirs restore in
waves, hibernated sessions survive a restart through the spill dir.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

H, N, C = 4, 48, 4
_ROW_KEYS = ("next_idx", "next_prob", "best", "pbest_max", "pbest_entropy")


@pytest.fixture(scope="module")
def task():
    from coda_tpu.data import make_synthetic_task

    return make_synthetic_task(seed=0, H=H, N=N, C=C)


def _app(task, capacity=4, warm=True, tiering=True, spill_dir=None,
         recorder=None, fault_spec=None, **kw):
    from coda_tpu.serve import SelectorSpec, ServeApp

    app = ServeApp(capacity=capacity, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=capacity),
                   tiering=tiering, tier_spill_dir=spill_dir,
                   recorder=recorder, fault_spec=fault_spec, **kw)
    app.add_task(task.name, task.preds)
    app.start(warm=warm)
    return app


def _drive(app, seed, rounds):
    out = app.open_session(seed=seed)
    sid = out["session"]
    for _ in range(rounds):
        out = app.label(sid, int(out["idx"]) % C)
    return sid


def _last_row(app, sid):
    return {k: app.store.get(sid).last[k] for k in _ROW_KEYS}


def _assert_rows_bitwise(a, b, what=""):
    for k in _ROW_KEYS:
        va, vb = a[k], b[k]
        if isinstance(va, float):
            assert np.float32(va).tobytes() == np.float32(vb).tobytes(), \
                (what, k, va, vb)
        else:
            assert va == vb, (what, k, va, vb)


# ---------------------------------------------------------------------------
# wake-from-warm / wake-from-cold: bitwise vs a never-demoted control
# ---------------------------------------------------------------------------

def test_wake_from_warm_bitwise_vs_control(task):
    """Demote a session mid-trajectory, continue it with labels (each
    transparently waking it), and pin the result bitwise — rows AND the
    full recorder stream — against a control session that never left the
    slab."""
    app = _app(task)
    try:
        sid = _drive(app, seed=5, rounds=3)
        assert app.tiers.try_demote(sid)
        st = app.stats()
        assert st["tiers"]["warm"] == 1
        assert st["open_sessions"] == 1 and st["slab_occupancy"] == 0
        # label the parked session: transparent wake through the snapshot
        # fast path (no replay), then two more rounds
        out = app.store  # noqa: F841  (documentation: sid not resident)
        cur = app.best(sid)  # best() wakes too
        assert app.metrics.wakes == 1
        assert app.metrics.wakes_from_warm == 1
        assert app.metrics.wakes_via_replay == 0
        for _ in range(2):
            cur = app.label(sid, int(cur["idx"]) % C)

        control = _drive(app, seed=5, rounds=5)
        _assert_rows_bitwise(_last_row(app, sid), _last_row(app, control),
                             "warm-woken vs control")
        rows_w = app.recorder.history(sid)
        rows_c = app.recorder.history(control)
        assert len(rows_w) == len(rows_c) == 6
        for rw, rc in zip(rows_w, rows_c):
            for k in _ROW_KEYS:
                assert rw[k] == rc[k], k  # floats: exact dict equality
    finally:
        app.drain(timeout=10)


def test_wake_from_cold_bitwise_vs_control(task, tmp_path):
    """Same pin through the cold tier: demote -> hibernate (payload in
    the spill log, recorder stream sealed) -> a label wakes it from the
    spill store -> continue -> bitwise vs the uninterrupted control."""
    app = _app(task, spill_dir=str(tmp_path / "spill"))
    try:
        sid = _drive(app, seed=9, rounds=3)
        assert app.tiers.try_demote(sid)
        assert app.tiers.hibernate(sid)
        st = app.stats()
        assert st["tiers"] == {"hot": 0, "warm": 0, "cold": 1}
        assert st["open_sessions"] == 1
        # v3 spill layout: sharded segment files + a sidecar index, not
        # one file per session
        assert [fn for fn in os.listdir(str(tmp_path / "spill"))
                if fn.startswith("seg_")]
        assert app.tiers._spill.sids() == [sid]

        cur = app.label(sid, int(_cold_payload(app, sid)))
        assert app.metrics.wakes_from_cold == 1
        assert sid not in app.tiers._spill  # woken frame tombstoned
        cur = app.label(sid, int(cur["idx"]) % C)

        control = _drive(app, seed=9, rounds=5)
        _assert_rows_bitwise(_last_row(app, sid), _last_row(app, control),
                             "cold-woken vs control")
        rows_w = app.recorder.history(sid)
        rows_c = app.recorder.history(control)
        assert len(rows_w) == len(rows_c) == 6
        for rw, rc in zip(rows_w, rows_c):
            for k in _ROW_KEYS:
                assert rw[k] == rc[k], k
    finally:
        app.drain(timeout=10)


def _cold_payload(app, sid):
    """The next label for a hibernated session, read from its payload
    (the client's handle: last proposed idx mod C)."""
    payload = app.tiers._spill.get(sid)
    return payload["last"]["next_idx"] % C


# ---------------------------------------------------------------------------
# demotion races: in-flight label tickets and exports pin the session
# ---------------------------------------------------------------------------

def test_demotion_loses_to_inflight_label_ticket(task):
    """A label ticket in flight holds the session's pin: a concurrent
    demotion must ABORT (state untouched, label applied exactly once);
    after the ticket resolves the demotion succeeds."""
    app = _app(task)
    try:
        out = app.open_session(seed=0)
        sid = out["session"]
        app.batcher.pause()
        sess, ticket = app._label_begin(sid, int(out["idx"]) % C, None)
        assert sess.pins == 1
        # demotion races the queued ticket: it must cleanly lose
        assert app.tiers.try_demote(sid) is False
        assert app.store.alive(sid)
        app.batcher.resume()
        res = ticket.wait(30.0)
        assert app.store.get(sid).n_labeled == 1  # applied exactly once
        assert sess.pins == 0                     # pin released on resolve
        assert app.metrics.demotions == 0
        # quiescent now: the same demotion wins
        assert app.tiers.try_demote(sid) is True
        assert not app.store.alive(sid) and app.tiers.parked(sid)
        # and the woken session continues from the post-label state
        nxt = app.label(sid, int(res["next_idx"]) % C)
        assert nxt["n_labeled"] == 2
    finally:
        app.drain(timeout=10)


def test_demotion_races_export_without_loss(task):
    """POST /export pins like any verb: a demotion racing it aborts; a
    demotion that already won serves the export FROM the parked payload
    (no wake), and close-on-export discards the parked copy."""
    app = _app(task)
    try:
        sid = _drive(app, seed=2, rounds=2)
        sess = app.store.get_pinned(sid)      # what the export verb holds
        try:
            assert app.tiers.try_demote(sid) is False
        finally:
            app.store.unpin(sess)
        assert app.tiers.try_demote(sid) is True
        # export of the parked session: the payload IS the export
        payload = app.export_session(sid)
        assert payload["session"] == sid
        assert payload["n_labeled"] == 2
        assert payload["carries"] is not None
        assert app.tiers.parked(sid)          # served without waking
        assert app.metrics.wakes == 0
        # a second server imports the parked export; continuing there
        # matches continuing the demoted session here
        b = _app(task)
        try:
            info = b.import_session(payload)
            assert info["restored_via"] == "snapshot"
            cont_b = b.label(sid, int(payload["last"]["next_idx"]) % C)
            cont_a = app.label(sid, int(payload["last"]["next_idx"]) % C)
            assert cont_a["n_labeled"] == cont_b["n_labeled"] == 3
            _assert_rows_bitwise(_last_row(app, sid), _last_row(b, sid),
                                 "parked-export import vs wake")
        finally:
            b.drain(timeout=10)
        # close-on-export of a parked session discards it
        assert app.tiers.try_demote(sid)
        app.export_session(sid, close=True)
        assert not app.tiers.parked(sid)
        from coda_tpu.serve import UnknownSession

        with pytest.raises(UnknownSession):
            app.store.get(sid)
    finally:
        app.drain(timeout=10)


# ---------------------------------------------------------------------------
# admission past capacity: demote-then-admit, 503 only without tiering
# ---------------------------------------------------------------------------

def test_admission_past_capacity_demotes_instead_of_503(task):
    app = _app(task, capacity=2)
    try:
        sids = [app.open_session(seed=s)["session"] for s in range(5)]
        st = app.stats()
        assert st["open_sessions"] == 5
        assert st["slab_occupancy"] == 2
        assert st["sessions_rejected"] == 0
        assert st["demotions"] >= 3
        # every session — resident or paged — still answers
        for sid in sids:
            assert app.best(sid)["session"] == sid
    finally:
        app.drain(timeout=10)


def test_no_tiering_keeps_slabfull_backpressure(task):
    """--no-tiering preserves the pre-tiering contract: sessions exist
    only while they hold a slab slot and admission past capacity raises
    SlabFull (the 503)."""
    from coda_tpu.serve import SlabFull

    app = _app(task, capacity=2, tiering=False)
    try:
        assert app.tiers is None
        for s in range(2):
            app.open_session(seed=s)
        with pytest.raises(SlabFull):
            app.open_session(seed=2)
        assert app.metrics.sessions_rejected == 1
    finally:
        app.drain(timeout=10)


# ---------------------------------------------------------------------------
# fault injection: demote_during_label
# ---------------------------------------------------------------------------

def test_fault_demote_during_label_exact_once(task):
    """The injected demotion-at-label race (``demote_during_label``):
    labels keep applying exactly once through forced paging, and the
    woken streams still replay bitwise."""
    from coda_tpu.serve import SessionStore
    from coda_tpu.serve.recovery import verify_session_stream

    app = _app(task, fault_spec="demote_during_label:every=2,times=8")
    try:
        out = app.open_session(seed=1)
        sid = out["session"]
        for _ in range(6):
            out = app.label(sid, int(out["idx"]) % C)
        assert app.store.get(sid).n_labeled == 6
        assert app.metrics.demotions >= 1 and app.metrics.wakes >= 1
        store = SessionStore(capacity=2)
        store.register_task(app.default_task,
                            app.store._tasks[app.default_task])
        meta = {"task": app.default_task, "method": app.spec.method,
                "spec_kwargs": [list(kv) for kv in app.spec.kwargs],
                "seed": 1}
        info = verify_session_stream(store, meta,
                                     app.recorder.history(sid), sid=sid)
        assert info["parity"] and info["rounds"] == 7
    finally:
        app.drain(timeout=10)


# ---------------------------------------------------------------------------
# observability: tier gauges/counters/ring on /stats and /metrics
# ---------------------------------------------------------------------------

def test_tier_metrics_on_stats_and_prometheus(task):
    from coda_tpu.telemetry import lint_prometheus, render_prometheus

    app = _app(task, capacity=2)
    try:
        sids = [app.open_session(seed=s)["session"] for s in range(3)]
        app.best(sids[0])     # wake (sids[0] was demoted by admission)
        st = app.stats()
        assert st["tiers"]["hot"] + st["tiers"]["warm"] \
            + st["tiers"]["cold"] == st["open_sessions"] == 3
        assert st["demotions"] >= 2 and st["wakes"] >= 1
        assert st["wake_latency"]["p99_ms"] is not None
        assert st["ring_fill"]["wake_latency"] >= 1
        text = render_prometheus(app.telemetry.registry,
                                 serve_metrics=app.metrics)
        for family in ("coda_serve_sessions_hot", "coda_serve_sessions_warm",
                       "coda_serve_sessions_cold",
                       "coda_serve_demotions_total",
                       "coda_serve_wakes_total",
                       "coda_serve_hibernates_total",
                       "coda_serve_wake_latency_seconds"):
            assert family in text, family
        assert lint_prometheus(text) == []
    finally:
        app.drain(timeout=10)


# ---------------------------------------------------------------------------
# restore across tiers
# ---------------------------------------------------------------------------

def test_crash_restore_waves_beyond_capacity(task, tmp_path):
    """A record dir holding MORE live streams than slab capacity restores
    whole with tiering: waves of capacity-many sessions replay coalesced,
    each wave pages out for the next — then every session answers (the
    tail waking on touch)."""
    from coda_tpu.serve.recovery import data_rows, load_session_stream
    from coda_tpu.telemetry import SessionRecorder

    d = str(tmp_path / "rec")
    app = _app(task, capacity=2,
               recorder=SessionRecorder(out_dir=d))
    try:
        sids = [_drive(app, seed=s, rounds=2) for s in range(5)]
    finally:
        # simulate sudden death: no drain, no close markers — just stop
        # ticking (the files keep their flushed rows)
        app.batcher.stop(drain=False, timeout=5)
        if app.tiers is not None:
            app.tiers.stop()
    # the on-disk streams are the authority (several sessions were already
    # paged warm by admission pressure on the first app — their streams
    # are parked, not closed)
    rows_before = {
        sid: data_rows(load_session_stream(
            os.path.join(d, f"session_{sid}.jsonl"))[1])
        for sid in sids
    }

    app2 = _app(task, capacity=2, recorder=SessionRecorder(out_dir=d))
    try:
        report = app2.restore_sessions(d)
        assert sorted(report["restored"]) == sorted(sids)
        assert report["failed"] == {}
        st = app2.stats()
        assert st["open_sessions"] == 5
        assert st["slab_occupancy"] <= 2
        # every restored session continues bitwise where it left off
        for sid in sids:
            hist = app2.recorder.history(sid) or \
                app2.tiers.parked_payload(sid)["rows"]
            assert len(hist) == len(rows_before[sid])
            out = app2.label(sid, int(hist[-1]["next_idx"]) % C)
            assert out["n_labeled"] == 3
    finally:
        app2.drain(timeout=10)


def test_hibernated_sessions_survive_restart(task, tmp_path):
    """Cold sessions live in the spill dir, not the process: a fresh app
    pointed at the same dir re-indexes them and a label wakes them."""
    spill = str(tmp_path / "spill")
    app = _app(task, spill_dir=spill)
    try:
        sid = _drive(app, seed=4, rounds=2)
        nxt = int(app.store.get(sid).last["next_idx"]) % C
        assert app.tiers.try_demote(sid) and app.tiers.hibernate(sid)
    finally:
        app.drain(timeout=10)

    app2 = _app(task, spill_dir=spill)
    try:
        assert app2.tiers.parked(sid)
        out = app2.label(sid, nxt)
        assert out["n_labeled"] == 3
        assert app2.metrics.wakes_from_cold == 1
    finally:
        app2.drain(timeout=10)


# ---------------------------------------------------------------------------
# spill store v3: sharded segments + sidecar index + lazy frames
# (serve/spill.py)
# ---------------------------------------------------------------------------

def test_spill_store_roundtrip_and_tombstones(tmp_path):
    """put/get/delete over the segment files: last write wins, tombstones
    delete, and a clean restart rebuilds the same view from the sidecar
    index ALONE (startup_mode 'index', zero frames re-scanned)."""
    from coda_tpu.serve.spill import SpillStore

    d = str(tmp_path / "spill")
    s = SpillStore(d)
    payloads = {f"{i:04x}": {"session": f"{i:04x}", "rows": [i] * 50}
                for i in range(100)}
    for sid, p in payloads.items():
        assert s.put(sid, p)
    assert len(s) == 100
    assert s.get("0007") == payloads["0007"]
    # supersede: a re-put of the same sid serves the NEW payload
    assert s.put("0007", {"session": "0007", "rows": [999]})
    assert s.get("0007")["rows"] == [999]
    assert s.delete("0003")
    assert s.get("0003") is None and "0003" not in s
    assert len(s) == 99
    s.close()
    # clean restart: the persisted index IS the state — no frame scan
    s2 = SpillStore(d)
    assert s2.startup_mode == "index"
    assert s2.startup_scan_frames == 0
    assert len(s2) == 99
    assert s2.get("0003") is None
    assert s2.get("0007")["rows"] == [999]
    assert s2.get("0042") == payloads["0042"]
    s2.close()


def test_spill_store_crash_restart_scans_only_the_tail(tmp_path):
    """A crash after the last index flush loses no frames: startup reads
    the sidecar, then scans ONLY the bytes appended past the recorded
    segment sizes — and a torn final frame (crash mid-append) is
    truncated without losing earlier frames."""
    import os

    from coda_tpu.serve.spill import SpillStore

    d = str(tmp_path / "spill")
    s = SpillStore(d)
    s.put("a", {"session": "a", "n": 1})
    s.put("b", {"session": "b", "n": 2})
    s.close()                       # index now records both frames
    # "crash" frames: append past the index without rewriting it, plus a
    # torn half-frame at the very end
    s = SpillStore(d)
    s.put("c", {"session": "c", "n": 3})
    seg = max(fn for fn in os.listdir(d) if fn.startswith("seg_"))
    s._append_fd.close()            # abandon without close(): no flush
    with open(os.path.join(d, seg), "ab") as f:
        f.write(b'{"sid": "torn", "parts": [["meta", 99999, 1]]}\nxx')
    s2 = SpillStore(d)
    assert s2.startup_mode == "index"      # sidecar honored...
    assert s2.startup_scan_frames >= 1     # ...tail scanned, not the world
    assert s2.get("a")["n"] == 1
    assert s2.get("c")["n"] == 3           # the post-flush frame survived
    assert "torn" not in s2
    s2.close()


def test_spill_store_compacts_per_segment(tmp_path, monkeypatch):
    """Dead frames (supersessions + tombstones) past the garbage
    threshold are compacted away one SEALED segment at a time — live
    frames copy forward into the active segment, the reclaimed file is
    unlinked, and no reader ever sees a stop-the-world pause."""
    import os

    from coda_tpu.serve import spill as spill_mod
    from coda_tpu.serve.spill import SpillStore

    monkeypatch.setattr(spill_mod, "SEGMENT_MAX_BYTES", 512)
    d = str(tmp_path / "spill")
    s = SpillStore(d)
    for i in range(30):
        s.put("churn", {"session": "churn", "n": i})  # 29 dead frames
    s.put("keep", {"session": "keep"})
    segs_before = {fn for fn in os.listdir(d) if fn.startswith("seg_")}
    assert len(segs_before) > 1     # the 512-byte cap sharded the stream
    size_before = sum(os.path.getsize(os.path.join(d, fn))
                      for fn in segs_before)
    n = s.maybe_compact()
    assert n >= 1 and s.segment_compactions == n
    segs_after = {fn for fn in os.listdir(d) if fn.startswith("seg_")}
    size_after = sum(os.path.getsize(os.path.join(d, fn))
                     for fn in segs_after)
    assert size_after < size_before
    assert s.get("churn")["n"] == 29       # last write still wins
    assert s.get("keep") == {"session": "keep"}
    s.close()
    s2 = SpillStore(d)                      # and the compacted dir reopens
    assert s2.get("churn")["n"] == 29
    assert s2.get("keep") == {"session": "keep"}
    s2.close()


def test_spill_store_reads_are_lazy_until_materialized(tmp_path):
    """A frame read is zero-copy until touched: the payload mapping comes
    back without decompressing the packed array leaves; materialize()
    restores the exact original JSON-safe payload."""
    import base64

    import numpy as np

    from coda_tpu.serve.spill import SpillStore, materialize

    arr = np.arange(4096, dtype=np.float32)
    packed = {"dtype": "float32", "shape": [4096],
              "data": base64.b64encode(arr.tobytes()).decode()}
    payload = {"session": "aa", "rows": [1, 2, 3],
               "carries": [packed, packed], "key": packed}
    d = str(tmp_path / "spill")
    s = SpillStore(d)
    assert s.put("aa", payload)
    got = s.get("aa")
    # meta is eager, the packed leaves are lazy wrappers…
    assert got["session"] == "aa" and got["rows"] == [1, 2, 3]
    leaf = got["carries"][0]
    assert leaf["dtype"] == "float32" and leaf["shape"] == [4096]
    # …whose raw bytes decode to the original array when finally pulled
    # (base64 framing only reappears at the serialization boundary)
    assert np.array_equal(np.frombuffer(leaf["data"], np.float32), arr)
    assert materialize(got) == payload
    s.close()


def test_spill_store_reads_and_folds_legacy_per_file_layout(tmp_path):
    """The v1 one-JSON-file-per-session layout is still readable, and
    startup compaction folds it into the log and removes the files —
    a v1 spill dir upgrades itself."""
    import os

    from coda_tpu.serve.spill import SpillStore

    d = str(tmp_path / "spill")
    os.makedirs(d)
    for i in range(3):
        with open(os.path.join(d, f"hibernated_{i:02x}.json"), "w") as f:
            json.dump({"session": f"{i:02x}", "legacy": True}, f)
    s = SpillStore(d)
    assert len(s) == 3
    assert s.get("01") == {"session": "01", "legacy": True}
    # folded into the log, per-file copies gone
    assert s.compactions == 1
    assert not [fn for fn in os.listdir(d)
                if fn.startswith("hibernated_")]
    assert s.get("02") == {"session": "02", "legacy": True}
    s.close()


def test_wake_from_legacy_hibernate_file(task, tmp_path):
    """A session hibernated by the v1 per-file layout wakes through a
    fresh app (the upgrade path: old spill dirs keep serving)."""
    import os

    from coda_tpu.serve.spill import materialize

    spill = str(tmp_path / "spill")
    app = _app(task, spill_dir=spill)
    try:
        sid = _drive(app, seed=11, rounds=2)
        nxt = int(app.store.get(sid).last["next_idx"]) % C
        assert app.tiers.try_demote(sid) and app.tiers.hibernate(sid)
        # pull the frame eagerly: the mmap behind the lazy view dies
        # with the store
        payload = materialize(app.tiers._spill.get(sid))
    finally:
        app.drain(timeout=10)
    # rewrite the hibernated payload in the V1 layout, drop v3 state
    for fn in os.listdir(spill):
        if fn.startswith("seg_") or fn == "spill_index.json":
            os.remove(os.path.join(spill, fn))
    with open(os.path.join(spill, f"hibernated_{sid}.json"), "w") as f:
        json.dump(payload, f)

    app2 = _app(task, spill_dir=spill)
    try:
        assert app2.tiers.parked(sid)
        out = app2.label(sid, nxt)
        assert out["n_labeled"] == 3
        assert app2.metrics.wakes_from_cold == 1
    finally:
        app2.drain(timeout=10)


# ---------------------------------------------------------------------------
# loadgen: zipf mode smoke (the tiering workload end to end, every PR)
# ---------------------------------------------------------------------------

def test_zipf_loadgen_smoke(tmp_path):
    import scripts.serve_loadgen as lg

    spill = str(tmp_path / "spill")
    args = lg.parse_args([
        "--synthetic", "4,48,4", "--method", "coda",
        "--zipf", "1.3", "--sessions", "24", "--workers", "6",
        "--labels", "2", "--capacity", "8", "--retries", "8",
        "--tier-spill-dir", spill, "--idle-warm-s", "2",
        "--idle-cold-s", "4", "--max-warm", "8",
        "--tier-free-frac", "0.25",
    ])
    report = lg.run_loadgen(args)
    assert report["n_errors"] == 0, report["errors"]
    assert report["mode"] == "zipf"
    t = report["tiering"]
    assert t["open_sessions"] == 24
    assert t["slab_occupancy"] <= 8
    assert t["admission_rejects"] == 0
    assert t["demotions"] >= 16
    assert t["wakes"] >= 1
    assert t["wake_failures"] == 0
    assert t["hot_hit_rate"] is not None
    assert t["peak_rss_bytes"] and t["peak_rss_bytes"] > 0
    assert t["wake_latency"]["p99_ms"] is not None
    assert t["tick_ms"] is not None
