"""Decision-quality plane (telemetry/quality.py): streaming calibration,
drift detectors, the shadow auditor, and their serve wiring.

The load-bearing guarantees:

  * calibration accumulators match hand-computed ECE/Brier on known traces;
  * CUSUM / Page-Hinkley fire and clear deterministically (injectable
    clock, no sleeps);
  * the shadow auditor reports ZERO divergences on a clean server and
    catches a single-ulp stream tamper (faults.py ``stream_tamper``) with
    exact session/round attribution;
  * quality-on vs quality-off produce BITWISE-identical decision rows —
    the only stream delta is the additive-optional ``pred_label_prob``;
  * every quality_* metrics family renders prometheus-lint-clean, single
    replica and fleet-merged;
  * the prior pool's staleness clock survives the snapshot/replace
    round-trip (the router exchange).
"""

import json
import math
import types

import numpy as np
import pytest

from coda_tpu.telemetry.quality import (
    CALIBRATION_MIN_SAMPLES,
    CalibrationBuckets,
    CalibrationMonitor,
    CusumDetector,
    PageHinkley,
    QualityPlane,
    default_drift_bank,
    pbest_calibration,
    quality_slos,
    reliability_curve,
    tamper_rows_ulp,
)

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


# ---------------------------------------------------------------------------
# calibration accumulators
# ---------------------------------------------------------------------------

def test_calibration_buckets_match_hand_computed_trace():
    # 4 observations, bins of width .1: (conf, hit)
    obs = [(0.95, True), (0.95, False), (0.55, True), (0.15, False)]
    bk = CalibrationBuckets(bins=10)
    for conf, hit in obs:
        bk.observe(conf, hit)
    # bin 9 holds two obs: conf .95, acc .5 -> |.5-.95|*2; bin 5 one obs
    # conf .55 acc 1 -> .45; bin 1 one obs conf .15 acc 0 -> .15
    expect_ece = (2 * abs(0.5 - 0.95) + 1 * abs(1.0 - 0.55)
                  + 1 * abs(0.0 - 0.15)) / 4
    assert bk.ece() == pytest.approx(expect_ece)
    expect_brier = np.mean([(0.95 - 1) ** 2, (0.95 - 0) ** 2,
                            (0.55 - 1) ** 2, (0.15 - 0) ** 2])
    assert bk.brier() == pytest.approx(expect_brier)
    snap = bk.snapshot()
    assert snap["n"] == 4
    assert snap["bins"][9]["n"] == 2
    assert snap["bins"][9]["accuracy"] == pytest.approx(0.5)
    assert snap["bins"][9]["confidence"] == pytest.approx(0.95)
    # perfectly calibrated stream -> ECE 0
    perfect = CalibrationBuckets(bins=1)
    for hit in [True, True, False, False]:
        perfect.observe(0.5, hit)
    assert perfect.ece() == pytest.approx(0.0)


def test_calibration_buckets_conf_one_lands_in_top_bin():
    bk = CalibrationBuckets(bins=10)
    bk.observe(1.0, True)  # must not index past the last bucket
    assert bk.snapshot()["bins"][9]["n"] == 1


def test_calibration_monitor_per_task_and_worst_ece():
    mon = CalibrationMonitor()
    for _ in range(CALIBRATION_MIN_SAMPLES):
        mon.observe("well", 0.5, True)   # acc 1 @ conf .5 -> ECE .5
        mon.observe("off", 0.9, False)   # acc 0 @ conf .9 -> ECE .9
    snap = mon.snapshot()
    assert set(snap) == {"off", "well"}
    assert snap["off"]["ece"] == pytest.approx(0.9)
    assert mon.worst_ece() == pytest.approx(0.9)
    # below the evidence floor no task may grade
    mon2 = CalibrationMonitor()
    mon2.observe("thin", 0.9, False)
    assert mon2.worst_ece() is None


def test_pbest_calibration_regret_zero_is_hit():
    pbest = np.array([[0.9, 0.8, 0.4, 0.6]])
    regret = np.array([[0.0, 0.1, 0.0, 0.0]])
    out = pbest_calibration(pbest, regret)
    assert out["n"] == 4
    # hits: rounds with regret 0 -> 3/4 accuracy overall
    acc = sum(b["n"] * (b["accuracy"] or 0) for b in out["bins"])
    assert acc == pytest.approx(3.0)
    # NaN pbest rounds (pre-warmup) are dropped, not counted
    out2 = pbest_calibration(np.array([np.nan, 0.7]), np.array([0.0, 0.0]))
    assert out2["n"] == 1


def test_record_calibration_adapts_run_records():
    from coda_tpu.engine.replay import record_calibration

    rec = types.SimpleNamespace(
        seeds=2,
        arrays={"pbest_max": np.array([[0.9, 0.8], [0.7, 0.6]]),
                "regret": np.array([[0.0, 0.2], [0.0, 0.0]])})
    out = record_calibration(rec)
    assert out["pooled"]["n"] == 4
    assert len(out["seeds"]) == 2
    assert out["seeds"][1]["n"] == 2


# ---------------------------------------------------------------------------
# drift detectors
# ---------------------------------------------------------------------------

def test_cusum_fires_and_clears_with_injectable_clock():
    t = [100.0]
    det = CusumDetector("d", mu0=0.1, k=0.05, h=0.5, clear=0.1,
                        clock=lambda: t[0])
    events = []
    for _ in range(3):  # s grows by 0.4 - 0.15 = 0.25 per sample
        t[0] += 1.0
        events.append(det.observe(0.4))
    assert events == [None, "fired", None]  # fires once crossing h, once
    assert det.firing and det.fired_total == 1
    assert det.last_transition_t == 102.0  # stamped at the crossing sample
    fired_at = det.last_transition_t
    for _ in range(10):  # in-control samples drain s by 0.15 each
        t[0] += 1.0
        ev = det.observe(0.0)
        if ev == "cleared":
            break
    assert not det.firing and det.cleared_total == 1
    assert det.last_transition_t > fired_at
    snap = det.snapshot()
    assert snap["kind"] == "cusum" and snap["fired_total"] == 1


def test_page_hinkley_fires_on_mean_shift_and_rebaselines():
    det = PageHinkley("ph", delta=0.005, lam=0.1, clock=lambda: 0.0)
    for _ in range(20):
        assert det.observe(0.1) is None  # stationary stream never fires
    fired = None
    for _ in range(50):
        fired = det.observe(0.5) or fired  # sustained shift
        if fired:
            break
    assert fired == "fired" and det.firing
    cleared = None
    for _ in range(20):
        # the stream reverts below the running mean: m drains, m_min
        # tracks it, ph collapses to 0 <= lam/2 -> clear + re-baseline
        cleared = det.observe(0.0) or cleared
        if cleared:
            break
    assert cleared == "cleared" and not det.firing
    assert det.cleared_total == 1
    # re-baselined on the clearing sample: a stationary stream at the
    # new level never fires again
    for _ in range(20):
        assert det.observe(0.0) is None


def test_default_drift_bank_names_and_feed():
    bank = default_drift_bank()
    assert set(bank.snapshot()) == {"surrogate_residual", "prior_staleness",
                                    "crowd_reliability"}
    assert bank.observe("unknown_detector", 1.0) is None
    assert not bank.any_firing()
    for _ in range(50):
        bank.observe("surrogate_residual", 1.0)
    assert bank.any_firing()


def test_gate_pressure_maps_margin_to_drift_observable():
    from coda_tpu.selectors.surrogate import SURROGATE_SCORE_TOL, gate_pressure

    assert gate_pressure(None) == 0.0
    assert gate_pressure(float("nan")) == 0.0
    assert gate_pressure(SURROGATE_SCORE_TOL) == 0.0  # full headroom
    assert gate_pressure(0.0) == pytest.approx(1.0)   # gate about to trip
    assert gate_pressure(-SURROGATE_SCORE_TOL) == pytest.approx(2.0)
    assert gate_pressure(10.0) == 0.0                 # clamped at 0


def test_crowd_accuracy_movement():
    from coda_tpu.crowd.reliability import accuracy_movement

    assert accuracy_movement([0.9, 0.5], [0.9, 0.5]) == 0.0
    assert accuracy_movement([0.9, 0.5], [0.7, 0.5]) == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# stream tampering
# ---------------------------------------------------------------------------

def test_tamper_rows_ulp_flips_exactly_one_quantity():
    rows = [{"next_idx": 3, "next_prob": 0.25, "pbest_max": 0.5,
             "pbest_entropy": 1.0, "do_update": True} for _ in range(5)]
    out = tamper_rows_ulp(rows)
    assert rows[2]["next_prob"] == 0.25  # caller's rows untouched
    changed = [i for i, (a, b) in enumerate(zip(rows, out)) if a != b]
    assert changed == [2]  # the middle row, one row only
    delta = abs(out[2]["next_prob"] - 0.25)
    assert 0 < delta < 1e-6  # a single float32 ulp
    # q-wide list rows tamper their first entry
    rows_q = [{"next_idx": [1, 2], "next_prob": [0.25, 0.5]}]
    out_q = tamper_rows_ulp(rows_q)
    assert out_q[0]["next_prob"][0] != 0.25
    assert out_q[0]["next_prob"][1] == 0.5


# ---------------------------------------------------------------------------
# serve integration: clean audits, tamper detection, bitwise pin
# ---------------------------------------------------------------------------

def _make_app(fault_spec=None, quality=True, audit_frac=1.0, capacity=4):
    from coda_tpu.serve import SelectorSpec, ServeApp

    app = ServeApp(capacity=capacity, max_wait=0.001, tiering=False,
                   spec=SelectorSpec.create("coda", n_parallel=capacity),
                   fault_spec=fault_spec, quality=quality,
                   quality_audit_frac=audit_frac)
    from coda_tpu.data import make_synthetic_task

    task = make_synthetic_task(seed=0, H=4, N=48, C=4)
    app.add_task(task.name, task.preds)
    app.start(warm=False)
    return app, task.name


def _drive(app, task, seeds=(0, 1), rounds=6):
    """Deterministic traffic; returns {sid: rows-captured-before-close}."""
    rng = np.random.default_rng(7)
    sids = [app.open_session(task=task, seed=s)["session"] for s in seeds]
    for _ in range(rounds):
        for sid in sids:
            app.label(sid, int(rng.integers(0, 4)))
    streams = {sid: [dict(r) for r in app.recorder.history(sid)]
               for sid in sids}
    for sid in sids:
        app.close_session(sid)
    return streams


def test_shadow_auditor_clean_server_zero_divergences():
    app, task = _make_app()
    try:
        _drive(app, task)
        assert app.quality.drain(30)
        snap = app.quality.snapshot()
        audit = snap["audit"]
        assert audit["audits_total"] == 2
        assert audit["divergences_total"] == 0
        assert audit["tampered_total"] == 0
        assert audit["rounds_verified"] > 0
        cal = snap["calibration"][task]
        assert cal["n"] == 12  # 6 rounds x 2 sessions
        assert 0.0 <= cal["ece"] <= 1.0
        assert 0.0 <= (cal["mean_pred_label_prob"] or 0.0) <= 1.0
        card = app.quality_scorecard()
        assert card["verdict"]["audit"] == "ok"
        assert card["verdict"]["drift"] == "ok"
    finally:
        app.drain(timeout=5)


def test_shadow_auditor_catches_single_ulp_tamper():
    app, task = _make_app(fault_spec="stream_tamper:every=1")
    try:
        streams = _drive(app, task, seeds=(3,), rounds=6)
        (sid,) = streams
        assert app.quality.drain(30)
        audit = app.quality.snapshot()["audit"]
        assert audit["tampered_total"] == 1
        assert audit["divergences_total"] == 1
        (div,) = audit["last_divergences"]
        # exact attribution: the tampered session, the tampered round
        assert div["session"] == sid
        n_rows = len([r for r in streams[sid] if "kind" not in r])
        assert div["round"] == n_rows // 2
        assert "recorded" in div["detail"]
        assert app.quality_scorecard()["verdict"]["audit"] == "diverged"
    finally:
        app.drain(timeout=5)


def test_quality_on_off_rows_bitwise_identical():
    app_on, task = _make_app(quality=True)
    try:
        rows_on = _drive(app_on, task)
    finally:
        app_on.drain(timeout=5)
    app_off, _ = _make_app(quality=False)
    try:
        assert app_off.quality is None
        rows_off = _drive(app_off, task)
    finally:
        app_off.drain(timeout=5)

    def canon(streams, strip):
        # session ids are random per server; compare streams in OPEN
        # order (dict preserves _drive's seed order), sid-free
        return [json.dumps([{k: v for k, v in r.items() if k not in strip}
                            for r in rows], sort_keys=True)
                for rows in streams.values()]

    # quality-off streams carry NO pred_label_prob key at all (absent,
    # not null — the trace_id contract)
    assert not any("pred_label_prob" in r
                   for rows in rows_off.values() for r in rows)
    on_update_rows = [r for rows in rows_on.values() for r in rows
                      if r.get("do_update")]
    assert on_update_rows and all("pred_label_prob" in r
                                  for r in on_update_rows)
    assert all(0.0 <= r["pred_label_prob"] <= 1.0 for r in on_update_rows)
    # and with the additive field stripped the streams are BITWISE equal
    assert canon(rows_on, {"pred_label_prob"}) \
        == canon(rows_off, {"pred_label_prob"})


def test_quality_stream_passes_schema_checker(tmp_path):
    import importlib.util
    import os

    fp = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "check_record_schema.py")
    spec = importlib.util.spec_from_file_location("check_record_schema", fp)
    crs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(crs)

    from coda_tpu.telemetry import SessionRecorder

    from coda_tpu.serve import SelectorSpec, ServeApp

    app = ServeApp(capacity=2, max_wait=0.001, tiering=False,
                   spec=SelectorSpec.create("coda", n_parallel=2),
                   recorder=SessionRecorder(out_dir=str(tmp_path)),
                   quality=True, quality_audit_frac=0.0)
    from coda_tpu.data import make_synthetic_task

    task = make_synthetic_task(seed=0, H=4, N=48, C=4)
    app.add_task(task.name, task.preds)
    app.start(warm=False)
    try:
        sid = app.open_session(task=task.name, seed=0)["session"]
        for lab in (0, 1, 2):
            app.label(sid, lab)
        app.close_session(sid)
    finally:
        app.drain(timeout=5)
    bad = crs.check_tree(str(tmp_path))
    assert bad == {}
    assert crs.check_tree.last_checked >= 1
    # and the checker does reject an out-of-range pred_label_prob
    assert crs._check_pred_label_prob(1.5)
    assert crs._check_pred_label_prob([0.5, "x"])
    assert crs._check_pred_label_prob(0.5) == ""
    assert crs._check_pred_label_prob([0.5, 1.0]) == ""


def test_quality_audit_sampling_is_deterministic():
    plane = QualityPlane(preds_fn=lambda name: None, audit_frac=0.5)
    picks = {sid: plane.should_audit(sid)
             for sid in (f"s{i:04x}" for i in range(64))}
    plane2 = QualityPlane(preds_fn=lambda name: None, audit_frac=0.5)
    assert picks == {sid: plane2.should_audit(sid) for sid in picks}
    assert 0 < sum(picks.values()) < len(picks)
    none = QualityPlane(preds_fn=lambda name: None, audit_frac=0.0)
    assert not any(none.should_audit(sid) for sid in picks)


# ---------------------------------------------------------------------------
# metrics exposition + SLO wiring
# ---------------------------------------------------------------------------

def test_quality_metric_families_lint_clean():
    from coda_tpu.telemetry.prometheus import lint, render, render_fleet

    app, task = _make_app()
    try:
        _drive(app, task, seeds=(0,), rounds=4)
        assert app.quality.drain(30)
        # drift families export only for detectors whose signal has fed
        # (absent-not-zero; an exact server has no surrogate pressure) —
        # feed one observation so the families exist to lint
        app.quality.observe_drift("crowd_reliability", 0.01)
        snap = app.stats()
        assert "quality" in snap
        text = render(app.telemetry.registry, serve_metrics=app.metrics)
        assert lint(text) == []
        assert "coda_quality_audits_total" in text
        assert "coda_quality_calibration_ece" in text
        assert "coda_quality_drift_firing" in text
        fleet = render_fleet({"r0": snap, "r1": dict(snap)},
                             registry=app.telemetry.registry)
        assert lint(fleet) == []
        assert 'coda_quality_audits_total{replica="r0"}' in fleet
        assert 'coda_quality_drift_statistic{detector=' in fleet
    finally:
        app.drain(timeout=5)


def test_quality_slos_fire_and_clear_through_sweeper():
    from coda_tpu.telemetry.slo import SloSweeper

    t = [0.0]
    sweeper = SloSweeper(quality_slos(), fast_s=10.0, slow_s=20.0,
                         clock=lambda: t[0])
    drift_snap = {"statistic": 9.0, "firing": True, "fired_total": 1,
                  "cleared_total": 0, "observations": 9, "kind": "cusum",
                  "last_value": 1.0}

    def fleet(firing):
        d = dict(drift_snap, firing=firing)
        return {"replicas": {"r0": {"quality": {
            "audit": {"audits_total": 4, "divergences_recent": 0},
            "calibration": {}, "drift": {"prior_staleness": d}}}}}

    events = []
    for _ in range(5):
        t[0] += 1.0
        events += sweeper.observe(fleet(True))
    assert [e["state"] for e in events] == ["firing"]
    assert events[0]["slo"] == "quality_drift"
    for _ in range(30):  # good samples push the bad ones out of the window
        t[0] += 1.0
        events += sweeper.observe(fleet(False))
    assert [e["state"] for e in events] == ["firing", "resolved"]
    snap = sweeper.snapshot()
    assert not snap["objectives"]["quality_drift"]["firing"]
    # objectives with no quality sections anywhere report no data
    s2 = SloSweeper(quality_slos(), clock=lambda: t[0])
    s2.observe({"replicas": {"r0": {"dispatches": 3}}})
    obj = s2.snapshot()["objectives"]["quality_audit_divergence"]
    assert obj["burn_fast"] is None


def test_quality_slo_divergence_probe_reads_recent_window():
    slos = {o.name: o for o in quality_slos()}
    div = slos["quality_audit_divergence"]
    clean = {"replicas": {"r0": {"quality": {
        "audit": {"audits_total": 3, "divergences_recent": 0}}}}}
    dirty = {"replicas": {"r0": {"quality": {
        "audit": {"audits_total": 3, "divergences_recent": 1}}}}}
    no_audits = {"replicas": {"r0": {"quality": {
        "audit": {"audits_total": 0, "divergences_recent": 0}}}}}
    assert div.probe(clean) == 0.0
    assert div.probe(dirty) == 1.0
    assert div.probe(no_audits) is None
    ece = slos["quality_calibration_ece"]
    good = {"replicas": {"r0": {"quality": {"calibration": {
        "t": {"n": CALIBRATION_MIN_SAMPLES, "ece": 0.05}}}}}}
    bad = {"replicas": {"r0": {"quality": {"calibration": {
        "t": {"n": CALIBRATION_MIN_SAMPLES, "ece": 0.6}}}}}}
    thin = {"replicas": {"r0": {"quality": {"calibration": {
        "t": {"n": 3, "ece": 0.9}}}}}}
    assert ece.probe(good) == 0.0
    assert ece.probe(bad) == 1.0
    assert ece.probe(thin) is None


def test_router_quality_scorecard_aggregates_replicas():
    from coda_tpu.serve.router import SessionRouter

    app, task = _make_app()
    try:
        _drive(app, task, seeds=(0,), rounds=4)
        assert app.quality.drain(30)
        router = SessionRouter({"a": app})
        card = router.quality_scorecard()
        assert card["role"] == "router"
        assert card["replicas"]["a"]["audit"]["audits_total"] == 1
        assert card["verdict"]["audit"] == "ok"
        # a replica without the plane is listed as disabled, not dropped
        app2, _ = _make_app(quality=False, capacity=2)
        try:
            router2 = SessionRouter({"a": app, "b": app2})
            card2 = router2.quality_scorecard()
            assert card2["replicas"]["b"] == {"enabled": False}
            assert card2["verdict"]["audit"] == "ok"
        finally:
            app2.drain(timeout=5)
    finally:
        app.drain(timeout=5)


def test_cli_quality_report_over_http(capsys):
    import threading

    from coda_tpu import cli
    from coda_tpu.serve import make_server

    app, task = _make_app(capacity=2)
    srv = make_server(app, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        _drive(app, task, seeds=(0,), rounds=4)
        assert app.quality.drain(30)
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        # --json: the raw scorecard (replica-shaped: its own plane)
        assert cli.main(["quality", "--url", url, "--json"]) == 0
        card = json.loads(capsys.readouterr().out)
        assert card["audit"]["audits_total"] == 1
        assert card["verdict"]["audit"] == "ok"
        # human report: healthy plane exits 0 and names the organs
        assert cli.main(["quality", "--url", url]) == 0
        text = capsys.readouterr().out
        assert "audit" in text and "calibration" in text
    finally:
        srv.shutdown()
        srv.server_close()
        app.drain(timeout=5)


# ---------------------------------------------------------------------------
# prior pool staleness (r20 satellite)
# ---------------------------------------------------------------------------

def test_prior_pool_staleness_clock_and_snapshot_roundtrip():
    from coda_tpu.selectors.surrogate import empty_prior, prior_to_dict
    from coda_tpu.serve.priors import PriorPool

    t = [1000.0]
    pool = PriorPool(min_rounds=0.0, clock=lambda: t[0])
    assert pool.staleness_seconds() is None
    assert pool.stats()["staleness_seconds"] is None
    from coda_tpu.selectors.surrogate import N_FEATURES

    fit = {"A": np.eye(N_FEATURES), "b": np.ones(N_FEATURES),
           "n": 50.0, "rounds": 40.0}
    assert pool.contribute("k1", fit)
    t[0] += 30.0
    assert pool.contribute("k2", fit)
    t[0] += 70.0
    ages = pool.pool_ages()
    assert ages["k1"] == pytest.approx(100.0)
    assert ages["k2"] == pytest.approx(70.0)
    assert pool.staleness_seconds() == pytest.approx(100.0)
    stats = pool.stats()
    assert stats["staleness_seconds"] == pytest.approx(100.0)
    assert stats["pool_ages_seconds"]["k2"] == pytest.approx(70.0)
    # ages survive the snapshot -> replace round-trip (router exchange)
    snap = pool.snapshot()
    pool2 = PriorPool(min_rounds=0.0, clock=lambda: t[0])
    pool2.replace(snap)
    assert pool2.staleness_seconds() == pytest.approx(100.0)
    # a pre-r20 snapshot (no touched map) reads as touched-now
    legacy = {"pools": {"k3": prior_to_dict(
        pool._pools["k1"])}, "sessions_contributed": 1}
    pool3 = PriorPool(min_rounds=0.0, clock=lambda: t[0])
    pool3.replace(legacy)
    assert pool3.pool_ages()["k3"] == pytest.approx(0.0)
    # merge_delta refreshes the key's clock too
    t[0] += 10.0
    pool2.merge_delta({"k1": prior_to_dict(pool._pools["k1"])})
    assert pool2.pool_ages()["k1"] == pytest.approx(0.0)
    assert pool2.pool_ages()["k2"] == pytest.approx(80.0)
    assert empty_prior().n == 0  # import sanity


def test_prior_staleness_surfaces_on_metrics():
    from coda_tpu.telemetry.prometheus import lint, render_fleet

    snap = {"prior_pool_staleness_seconds": 42.5,
            "prior_pool_ages_seconds": {"t:abc": 42.5, "t:def": 1.25}}
    text = render_fleet({"r0": snap})
    assert lint(text) == []
    assert 'coda_serve_prior_pool_staleness_seconds{replica="r0"} 42.5' \
        in text
    assert 'coda_serve_prior_pool_age_seconds{pool="t:def",replica="r0"}' \
        in text


# ---------------------------------------------------------------------------
# plane snapshot / store flush
# ---------------------------------------------------------------------------

def test_quality_plane_log_to_store(tmp_path):
    from coda_tpu.tracking import TrackingStore

    plane = QualityPlane(preds_fn=lambda name: None)
    plane.calibration.observe("t", 0.9, True, p_label=0.9)
    plane.observe_drift("surrogate_residual", 0.2)
    store = TrackingStore(str(tmp_path / "db.sqlite"))
    plane.log_to_store(store)
    found = store.find_run("serve_quality", "quality-snapshot")
    assert found
    uuid = found[0]
    assert store.metric_series(uuid, "calibration_n.t") == [(0, 1.0)]
    assert store.metric_series(
        uuid, "drift_firing.surrogate_residual") == [(0, 0.0)]
