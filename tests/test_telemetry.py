"""Unified telemetry (``coda_tpu/telemetry``): span recorder correctness
under the multi-device scheduler, Chrome-trace round-trip, the Prometheus
``/metrics`` surface over real HTTP, recompile/HBM counters, ServeMetrics
ring-wrap percentile sanity, StepTimer thread-safety, and the repo-wide
clock-discipline static check — all tier-1, CPU-only (8 virtual devices
via conftest)."""

from __future__ import annotations

import http.client
import json
import re
import threading
import time

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# span recorder
# ---------------------------------------------------------------------------

def test_span_recorder_nesting_lanes_chrome_roundtrip(tmp_path):
    from coda_tpu.telemetry import SpanRecorder

    rec = SpanRecorder()
    with rec.span("outer", lane="host:main", phase="a"):
        with rec.span("inner", lane="host:main"):
            time.sleep(0.002)
    rec.record("dispatch", lane="device:0", t_start=1.0, t_end=1.5,
               attrs={"method": "coda"})
    rec.instant("marker", lane="device:1")

    assert rec.lanes() == ["host:main", "device:0", "device:1"]
    # inner finished first but nests inside outer's interval
    events = {name: (t0, t1) for name, lane, t0, t1, _ in rec.events()}
    assert events["outer"][0] <= events["inner"][0]
    assert events["inner"][1] <= events["outer"][1]

    # chrome export round-trips through JSON and keeps lane identity
    path = rec.save(str(tmp_path / "trace.json"))
    chrome = json.loads(open(path).read())
    evs = chrome["traceEvents"]
    meta = {e["args"]["name"]: e["tid"] for e in evs
            if e["ph"] == "M" and e["name"] == "thread_name"}
    assert set(meta) == {"host:main", "device:0", "device:1"}
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(xs) == {"outer", "inner", "dispatch", "marker"}
    assert xs["dispatch"]["tid"] == meta["device:0"]
    assert xs["dispatch"]["dur"] == pytest.approx(0.5e6)
    assert xs["inner"]["dur"] <= xs["outer"]["dur"]
    assert xs["outer"]["args"] == {"phase": "a"}
    assert xs["marker"]["dur"] == 0.0


def test_span_recorder_thread_safe_and_bounded():
    from coda_tpu.telemetry import SpanRecorder

    rec = SpanRecorder(capacity=256)

    def worker(i):
        for j in range(100):
            rec.record(f"w{i}", lane=f"lane{i % 3}",
                       t_start=j, t_end=j + 1)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = rec.summary()
    assert s["recorded"] == 800          # no lost updates
    assert s["events"] == 256            # ring keeps only the newest
    assert s["dropped"] == 800 - 256
    assert sorted(s["lanes"]) == ["lane0", "lane1", "lane2"]


def test_span_lane_busy_folds_overlaps():
    from coda_tpu.telemetry import SpanRecorder

    rec = SpanRecorder()
    rec.record("a", "device:0", 0.0, 2.0)
    rec.record("b", "device:0", 1.0, 3.0)   # overlap counted once
    rec.record("c", "device:0", 5.0, 6.0)
    assert rec.lane_busy_s("device:0") == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# registry + prometheus exposition
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (-?[0-9.eE+]+|NaN|[+-]Inf)"
    r"( # \{[^{}]*\} (-?[0-9.eE+]+|NaN|[+-]Inf))?$")  # optional exemplar


def _validate_exposition(text: str) -> dict:
    """Basic format validation; returns {metric name: [sample lines]}."""
    assert text.endswith("\n")
    seen_type: dict = {}
    samples: dict = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "summary", "untyped"), line
            assert name not in seen_type, f"duplicate TYPE for {name}"
            seen_type[name] = kind
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
        name = re.split(r"[{ ]", line, 1)[0]
        samples.setdefault(name, []).append(line)
    return samples


def test_registry_prometheus_exposition():
    from coda_tpu.telemetry import Registry, render_prometheus

    reg = Registry()
    reg.counter("thing_total", "things that happened").inc(3)
    g = reg.gauge("hbm_bytes", "per-device bytes")
    g.set(100, device="0")
    g.set_max(250, device="1")
    g.set_max(200, device="1")   # watermark keeps the max
    text = render_prometheus(reg)
    samples = _validate_exposition(text)
    assert 'coda_thing_total 3' in samples["coda_thing_total"]
    assert 'coda_hbm_bytes{device="0"} 100' in samples["coda_hbm_bytes"]
    assert 'coda_hbm_bytes{device="1"} 250' in samples["coda_hbm_bytes"]
    with pytest.raises(ValueError):
        reg.counter("thing_total").inc(-1)
    with pytest.raises(TypeError):
        reg.gauge("thing_total")   # kind mismatch fails loudly


def test_jit_recompile_counter_via_monitoring():
    """A fresh jit compile must tick the jax.monitoring-backed counter
    (unique shape so neither the in-process nor the persistent cache can
    satisfy it without a backend compile)."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.telemetry import Telemetry

    tele = Telemetry()   # installs the hooks on the process registry
    assert tele.hooks_live  # this jax exposes jax.monitoring
    c = tele.registry.counter("jit_compiles_total")
    before = c.value()
    n = 17 + int(before) % 3  # vary so reruns in-process still compile
    jax.jit(lambda x: x * 2.5 + 1)(jnp.ones((3, n))).block_until_ready()
    assert c.value() > before
    assert tele.registry.counter("jit_compile_seconds_total").value() > 0
    snap = tele.snapshot()
    assert snap["jit"]["recompiles"] == c.value()
    assert snap["jit"]["source"] == "jax.monitoring"


def test_jit_hooks_bind_every_hooked_registry():
    """A Telemetry built on a CUSTOM registry after hooks are already live
    on the process registry must still receive compile events (the one
    jax.monitoring listener fans out to every hooked registry), and
    hooks_live must be per-registry truth, not global listener state."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.telemetry import Registry, Telemetry

    Telemetry()   # hooks the process registry first
    custom = Telemetry(registry=Registry())
    assert custom.hooks_live
    unhooked = Telemetry(registry=Registry(), install_hooks=False)
    assert not unhooked.hooks_live
    assert unhooked.snapshot()["jit"]["source"] == \
        "cold-attribution-fallback"
    c = custom.registry.counter("jit_compiles_total")
    before = c.value()
    jax.jit(lambda x: x - 0.125)(jnp.ones((2, 23))).block_until_ready()
    assert c.value() > before
    assert custom.snapshot()["jit"]["recompiles"] == c.value()


def test_sample_device_memory_graceful_on_cpu():
    """CPU devices report memory_stats() None: sampling must return {} and
    register no gauges rather than fail (HBM evidence is TPU-only)."""
    from coda_tpu.telemetry import Registry, sample_device_memory

    reg = Registry()
    out = sample_device_memory(reg)
    assert out == {}
    assert reg.gauge("device_peak_bytes").samples() == []


# ---------------------------------------------------------------------------
# scheduler mesh: spans reproduce the occupancy evidence
# ---------------------------------------------------------------------------

def test_scheduler_spans_lanes_match_occupancy(tmp_path):
    """Scheduled run on the 8-virtual-device mesh: every dispatch lands on
    its device's lane, the Chrome export round-trips, and folding each
    lane's spans reproduces the scheduler's occupancy numbers exactly
    (same intervals, same union folding)."""
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.telemetry import Registry, SpanRecorder, Telemetry

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple virtual devices")
    tele = Telemetry(out_dir=str(tmp_path), registry=Registry(),
                     spans=SpanRecorder(), install_hooks=False)
    tasks = [make_synthetic_task(seed=s, H=4, N=40, C=3, name=f"t{s}")
             for s in range(3)]
    runner = SuiteRunner(iters=3, seeds=2, telemetry=tele)
    results = runner.run_batched([tasks], ["iid", "uncertainty"],
                                 devices="auto", progress=lambda s: None)
    assert len(results) == 6
    stats = runner.last_stats

    # device lanes only for devices that actually dispatched
    lanes = [ln for ln in tele.spans.lanes() if ln.startswith("device:")]
    dispatched = {f"device:{r['device']}" for r in stats["pairs"]}
    assert set(lanes) == dispatched and lanes

    # per-lane busy time reproduces the scheduler's occupancy (the
    # acceptance criterion: trace.json IS the occupancy evidence)
    wall = stats["compute_s"]
    for lane in lanes:
        did = int(lane.split(":", 1)[1])
        occ = tele.spans.lane_busy_s(lane) / wall
        assert occ == pytest.approx(stats["occupancy"][did], abs=2e-3)

    # dispatch spans carry the timeline's attribution
    ev_attrs = [a for name, ln, t0, t1, a in tele.spans.events()
                if ln.startswith("device:")]
    assert all({"method", "tasks", "cold"} <= set(a) for a in ev_attrs)

    # cold attribution fed the fallback recompile counter
    n_cold = sum(1 for p in stats["pairs"] if p["cold"])
    assert n_cold > 0
    # pairs records are per task; the counter ticks per dispatch
    assert tele.registry.counter("suite_cold_dispatches_total").value() > 0

    # artifacts: Perfetto-loadable trace.json + telemetry.json
    paths = tele.write(extra={"suite": {"occupancy": stats["occupancy"]}})
    chrome = json.load(open(paths["trace"]))
    assert {e["ph"] for e in chrome["traceEvents"]} <= {"M", "X"}
    snap = json.load(open(paths["telemetry"]))
    assert snap["suite"]["occupancy"]
    assert snap["jit"]["cold_dispatches"] > 0
    # exposition dump parses too
    _validate_exposition(open(paths["prometheus"]).read())


def test_serial_suite_records_host_spans():
    """The serial runner records one span per task-method pair (host lane
    semantics: blocking dispatch == device:0 lane)."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.telemetry import Registry, SpanRecorder, Telemetry

    tele = Telemetry(registry=Registry(), spans=SpanRecorder(),
                     install_hooks=False)
    t = make_synthetic_task(seed=1, H=4, N=40, C=3, name="alpha")
    runner = SuiteRunner(iters=3, seeds=2, telemetry=tele)
    runner.run([t], ["iid"], progress=lambda s: None)
    names = [name for name, *_ in tele.spans.events()]
    assert "alpha/iid" in names


# ---------------------------------------------------------------------------
# /metrics over HTTP
# ---------------------------------------------------------------------------

def test_metrics_endpoint_http_exposition():
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.serve import ServeApp, SelectorSpec, make_server

    task = make_synthetic_task(seed=0, H=5, N=48, C=4)
    app = ServeApp(capacity=3, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=3))
    app.add_task("tiny", task.preds)
    app.start()
    srv = make_server(app, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    port = srv.server_address[1]
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/session", body=json.dumps({"seed": 0}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        sid = json.loads(resp.read())["session"]
        conn.request("POST", f"/session/{sid}/label", body=json.dumps(
            {"label": 0}), headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        # keep-alive front door: the body must be drained before the next
        # response on this connection (HTTP/1.1 semantics)
        resp.read()
        assert resp.status in (200, 504)

        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        text = resp.read().decode()
        conn.close()
    finally:
        srv.shutdown()
        srv.server_close()
        app.drain(timeout=5.0)

    samples = _validate_exposition(text)
    # the acceptance surface: dispatches, occupancy, queue depth, latency
    # quantiles — plus the registry side (recompiles observed this process)
    assert samples["coda_serve_dispatches_total"]
    assert samples["coda_serve_requests_total"]
    assert samples["coda_serve_mean_occupancy"]
    assert samples["coda_serve_mean_queue_depth"]
    quant = " ".join(samples["coda_serve_request_latency_seconds"])
    assert 'quantile="0.5"' in quant and 'quantile="0.99"' in quant
    assert samples["coda_serve_request_latency_seconds_count"]
    assert float(samples["coda_serve_dispatches_total"][0].split()[-1]) >= 1
    assert samples["coda_jit_compiles_total"]  # ServeApp installs the hooks


# ---------------------------------------------------------------------------
# ServeMetrics: monotonic uptime + ring wrap
# ---------------------------------------------------------------------------

def test_serve_metrics_ring_wrap_percentiles():
    """Past ring capacity the window slides: percentiles reflect only the
    newest _RING events, and the snapshot reports fill == capacity."""
    from coda_tpu.serve import ServeMetrics
    from coda_tpu.serve.metrics import _RING

    m = ServeMetrics()
    # old regime: slow 100 ms dispatches — must be fully evicted below
    for _ in range(1000):
        m.record_dispatch(n_requests=1, queue_depth=9, seconds=0.1)
    # new regime: exactly _RING fast 1 ms dispatches
    for _ in range(_RING):
        m.record_dispatch(n_requests=2, queue_depth=1, seconds=0.001)
        m.record_request_latency(0.002)
    snap = m.snapshot()
    assert snap["dispatches"] == 1000 + _RING       # counters never window
    assert snap["ring_capacity"] == _RING
    assert snap["ring_fill"]["dispatch_latency"] == _RING
    assert snap["ring_fill"]["request_latency"] == _RING
    # every old 100 ms value fell out of the window
    assert snap["dispatch_latency"]["max_ms"] == pytest.approx(1.0)
    assert snap["dispatch_latency"]["p50_ms"] == pytest.approx(1.0)
    assert snap["dispatch_latency"]["p99_ms"] == pytest.approx(1.0)
    assert snap["mean_occupancy"] == pytest.approx(2.0)
    assert snap["mean_queue_depth"] == pytest.approx(1.0)
    assert snap["uptime_s"] >= 0.0   # monotonic baseline


def test_serve_metrics_uptime_monotonic_clock():
    """The baseline is time.monotonic(), not wall clock: uptime must be a
    small positive duration even if the wall clock were stepped."""
    from coda_tpu.serve import ServeMetrics

    m = ServeMetrics()
    time.sleep(0.01)
    up = m.snapshot()["uptime_s"]
    assert 0.0 < up < 60.0
    assert m.started <= time.monotonic()


# ---------------------------------------------------------------------------
# StepTimer thread-safety + extrema
# ---------------------------------------------------------------------------

def test_steptimer_thread_safe_min_max():
    from coda_tpu.utils.profiling import StepTimer

    timer = StepTimer()

    def worker():
        for _ in range(200):
            with timer.span("tick"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = timer.summary()["tick"]
    assert s["steps"] == 1600            # no lost read-modify-writes
    assert 0.0 <= s["min_s"] <= s["max_s"]
    assert s["seconds"] >= s["min_s"] * 1600 * 0.5


# ---------------------------------------------------------------------------
# clock discipline (CI static check)
# ---------------------------------------------------------------------------

def _load_check_clocks():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "check_clocks",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_clocks.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_clocks_repo_is_clean():
    """Tier-1 wiring of scripts/check_clocks.py: no unannotated wall-clock
    reads anywhere under coda_tpu/ (durations use perf_counter/monotonic)."""
    import os

    mod = _load_check_clocks()
    root = os.path.join(os.path.dirname(__file__), "..", "coda_tpu")
    assert mod.check_tree(root) == {}


def test_check_clocks_flags_violations(tmp_path):
    mod = _load_check_clocks()
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n"
        "t0 = time.time()\n"                       # violation
        "ts = time.time()  # wall-clock: epoch\n"  # annotated: allowed
        "# wall-clock: epoch stamp below\n"
        "ts2 = time.time()\n"                      # preceding-line pragma
        "from datetime import datetime\n"
        "now = datetime.now()\n")                  # violation
    v = mod.check_file(str(bad))
    assert [ln for ln, _ in v] == [2, 7]
    assert mod.main([str(tmp_path)]) == 1
    ok = tmp_path / "ok.py"
    bad.unlink()
    ok.write_text("import time\nt = time.perf_counter()\n")
    assert mod.main([str(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# driver plumbing: --telemetry-dir artifacts end to end
# ---------------------------------------------------------------------------

def test_cli_telemetry_dir_artifacts(tmp_path):
    from coda_tpu import cli

    out = tmp_path / "tele"
    cli.main(["--synthetic", "4,32,3", "--method", "iid", "--iters", "3",
              "--seeds", "2", "--no-mlflow",
              "--telemetry-dir", str(out)])
    trace = json.load(open(out / "trace.json"))
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {"load_dataset", "experiment"} <= names
    snap = json.load(open(out / "telemetry.json"))
    assert snap["run"]["method"] == "iid"
    assert snap["jit"]["source"] in ("jax.monitoring",
                                     "cold-attribution-fallback")
    _validate_exposition(open(out / "metrics.prom").read())


def test_run_suite_telemetry_flushes_store(tmp_path):
    """run_suite --telemetry-dir writes artifacts AND flushes the scalar
    registry into the tracking DB next to the experiment metrics."""
    import importlib.util
    import os

    from coda_tpu.data import make_synthetic_task

    npdir = tmp_path / "preds"
    npdir.mkdir()
    t = make_synthetic_task(seed=1, H=4, N=40, C=3, name="alpha")
    np.savez(npdir / "alpha.npz", preds=np.asarray(t.preds),
             labels=np.asarray(t.labels))
    spec = importlib.util.spec_from_file_location(
        "run_suite", os.path.join(os.path.dirname(__file__), "..",
                                  "scripts", "run_suite.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    db = str(tmp_path / "db.sqlite")
    out = tmp_path / "tele"
    mod.main(["--pred-dir", str(npdir), "--db", db, "--methods", "iid",
              "--seeds", "2", "--iters", "3",
              "--telemetry-dir", str(out)])
    assert (out / "trace.json").exists()
    snap = json.load(open(out / "telemetry.json"))
    assert snap["suite"]["total_s"] > 0
    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(db)
    rows = store.query(
        """SELECT m.key FROM metrics m JOIN runs r ON r.run_uuid=m.run_uuid
           JOIN experiments e ON e.experiment_id=r.experiment_id
           WHERE e.name='suite'""")
    keys = {k for (k,) in rows}
    assert "suite_cold_dispatches_total" in keys
    store.close()


# ---------------------------------------------------------------------------
# performance observatory: cost attribution + exposition lint + RSS fallback
# ---------------------------------------------------------------------------

def test_prometheus_lint_clean_on_rendered_output():
    """lint() accepts everything render() produces — including escaped
    label values, NaN/±Inf formatting, and summary _count lines."""
    from coda_tpu.telemetry import Registry, lint_prometheus, render_prometheus

    reg = Registry()
    reg.counter("events_total", 'help with "quotes"\nand newline').inc(2)
    g = reg.gauge("weird_labels", "label-escape coverage")
    g.set(1.5, path='a\\b', name='say "hi"\nthere')
    reg.gauge("extremes", "non-finite values").set(float("nan"), kind="n")
    reg.gauge("extremes").set(float("inf"), kind="p")
    reg.gauge("extremes").set(float("-inf"), kind="m")
    text = render_prometheus(reg)
    assert lint_prometheus(text) == []
    _validate_exposition(text)


def test_prometheus_lint_catches_violations():
    from coda_tpu.telemetry import lint_prometheus

    # sample with no TYPE header
    assert any("no TYPE" in v for v in lint_prometheus("orphan 1\n"))
    # duplicate family (re-opened after another family interleaved)
    dup = ("# TYPE a gauge\na 1\n# TYPE b gauge\nb 1\n"
           "# TYPE a gauge\na 2\n")
    out = lint_prometheus(dup)
    assert any("duplicate TYPE" in v for v in out)
    # HELP after TYPE is out of order
    assert any("after its TYPE" in v for v in lint_prometheus(
        "# TYPE c gauge\n# HELP c help\nc 1\n"))
    # unescaped quote in a label value
    assert any("labels" in v for v in lint_prometheus(
        '# TYPE d gauge\nd{k="a"b"} 1\n'))
    # a bad value
    assert any("bad value" in v or "unparseable" in v
               for v in lint_prometheus("# TYPE e gauge\ne nope\n"))
    # lowercase "nan" is NOT the canonical spelling
    assert lint_prometheus("# TYPE f gauge\nf NaN\n") == []
    assert lint_prometheus("# TYPE f gauge\nf nan\n") != []


def test_cost_harvest_roofline_and_metric_families():
    """harvest_executable_cost on a real compiled program records FLOPs/
    bytes/peak-HBM + a roofline class, feeds the executable_* gauge
    families, and the rendered exposition (with the new families) lints
    clean."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.telemetry import (
        COSTS,
        Registry,
        harvest_executable_cost,
        lint_prometheus,
        render_prometheus,
    )

    reg = Registry()
    compiled = jax.jit(lambda x: (x @ x.T).sum()).lower(
        jnp.ones((32, 64))).compile()
    entry = harvest_executable_cost(compiled, "test/matmul", site="engine",
                                    registry=reg)
    assert entry is not None
    assert np.isfinite(entry["flops"]) and entry["flops"] > 0
    assert np.isfinite(entry["bytes_accessed"]) and \
        entry["bytes_accessed"] > 0
    assert entry["peak_hbm_bytes"] > 0
    assert entry["roofline_class"] in ("compute-bound", "memory-bound")
    assert np.isfinite(entry["arithmetic_intensity"])
    assert np.isfinite(entry["machine_balance"])
    # an unknown device kind (CPU container) uses the documented default
    # balance and says so — never a fabricated silicon peak
    if entry["peak_source"] == "default_balance":
        assert entry["peak_flops_per_sec"] is None
    assert COSTS.get("test/matmul") == entry
    text = render_prometheus(reg)
    for fam in ("coda_executable_flops", "coda_executable_bytes_accessed",
                "coda_executable_peak_hbm_bytes",
                "coda_executable_roofline"):
        assert fam in text, fam
    assert lint_prometheus(text) == []


def test_cost_tracked_matches_plain_jit_bitwise():
    """The suite's CostTracked wrapper (AOT compile-and-reuse) returns
    bitwise the plain jit path's results, records one cost entry per
    argument signature, and degrades to plain jit when disabled."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.telemetry import COSTS, CostTracked, Registry
    from coda_tpu.telemetry import costs as costs_mod

    def f(x, y):
        return jnp.sin(x) @ y + x.sum()

    x = jnp.linspace(0, 1, 12 * 8).reshape(12, 8)
    y = jnp.ones((8, 3))
    ref = np.asarray(jax.jit(f)(x, y))
    reg = Registry()
    tracked = CostTracked(jax.jit(f), name="test/tracked", site="suite",
                          registry=reg)
    got = np.asarray(tracked(x, y))
    assert got.tobytes() == ref.tobytes()
    # second call reuses the compiled executable (still bitwise)
    assert np.asarray(tracked(x, y)).tobytes() == ref.tobytes()
    entries = {k: v for k, v in COSTS.snapshot(site="suite").items()
               if k.startswith("test/tracked@")}
    assert len(entries) == 1
    (entry,) = entries.values()
    assert entry["flops"] > 0 and entry["roofline_class"] in (
        "compute-bound", "memory-bound")
    # a new signature compiles (and records) separately
    x2 = jnp.ones((5, 8))
    assert np.asarray(tracked(x2, y)).tobytes() == np.asarray(
        jax.jit(f)(x2, y)).tobytes()
    assert len([k for k in COSTS.snapshot(site="suite")
                if k.startswith("test/tracked@")]) == 2
    # kill switch: no new entries, plain jit path
    costs_mod.set_enabled(False)
    try:
        x3 = jnp.ones((7, 8))
        assert np.asarray(tracked(x3, y)).tobytes() == np.asarray(
            jax.jit(f)(x3, y)).tobytes()
        assert len([k for k in COSTS.snapshot(site="suite")
                    if k.startswith("test/tracked@")]) == 2
    finally:
        costs_mod.set_enabled(True)


def test_suite_and_engine_cost_attribution_land_in_telemetry(tmp_path):
    """The two non-serve compile sites: SuiteRunner's jitted programs and
    the engine entry both land in the cost book, and telemetry.json
    carries the costs section."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.loop import run_seeds_compiled
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.selectors import SELECTOR_FACTORIES
    from coda_tpu.telemetry import COSTS, Telemetry

    task = make_synthetic_task(seed=3, H=4, N=48, C=4)
    runner = SuiteRunner(iters=3, seeds=2)
    runner.run_one("uncertainty", task)
    suite_entries = [k for k in COSTS.snapshot(site="suite")
                     if k.startswith("suite/uncertainty/")]
    assert suite_entries, "suite compile site recorded nothing"

    from coda_tpu.losses import LOSS_FNS

    run_seeds_compiled(
        lambda p: SELECTOR_FACTORIES["iid"](p, loss_fn=LOSS_FNS["acc"]),
        task.preds, task.labels, iters=3, seeds=2, cost_label="iid")
    assert any(k.startswith("engine/run_seeds/iid/4x48x4/")
               for k in COSTS.snapshot(site="engine"))

    tele = Telemetry(out_dir=str(tmp_path / "t"))
    paths = tele.write()
    snap = json.load(open(paths["telemetry"]))
    assert "costs" in snap and suite_entries[0] in snap["costs"]
    entry = snap["costs"][suite_entries[0]]
    assert entry["flops"] > 0 and "roofline_class" in entry


def test_rss_fallback_gauge_on_cpu():
    """CPU backends report no device memory_stats; the sampler then
    records the process-RSS gauge labeled source="rss" — memory evidence
    that stays distinct from the device_* families."""
    from coda_tpu.telemetry import Registry, sample_device_memory

    reg = Registry()
    out = sample_device_memory(reg)
    assert out == {}  # device sample contract unchanged
    assert reg.gauge("device_peak_bytes").samples() == []
    samples = reg.gauge("process_rss_bytes").samples()
    assert len(samples) == 1
    labels, value = samples[0]
    assert labels == {"source": "rss"}
    assert value > 0
    peak = reg.gauge("process_peak_rss_bytes").samples()
    assert peak and peak[0][0] == {"source": "rss"}
