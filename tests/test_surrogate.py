"""The contract-gated EIG surrogate rung (--eig-scorer surrogate:k).

What tier-1 pins here (ISSUE 15):

  * the DEFAULT is exact and bitwise-unchanged for every selector — the
    knob at 'exact' runs the identical program;
  * surrogate:k >= N is the exact-parity configuration (bitwise, the
    same ladder idiom as sparse:K >= C);
  * the shortlist-rows-are-exact property: the selected index's score is
    always the exact chain's value, never the surrogate's raw
    prediction;
  * a forced contract violation trips the fallback and the round's
    scores are bitwise the exact round's;
  * the real-digits 100-round trace stays inside the committed regret
    envelope vs the exact scorer;
  * q-wide (--acq-batch) and sparse-tier composition;
  * the serve bucket compiles the rung and session export/import
    round-trips the fit state bitwise;
  * the resolve_eig_mode auto budget charges the scorer tier (the
    C=1000 x H=2000 boundary pinned both ways);
  * recorder/replay: eig_scorer fingerprinted, v3 streams carry the
    per-round fallback flag, surrogate-vs-exact triages as
    eig-scorer-envelope, bitwise self-replay.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from coda_tpu.data import make_synthetic_task
from coda_tpu.engine.loop import run_seeds_compiled
from coda_tpu.losses import accuracy_loss
from coda_tpu.oracle import true_losses
from coda_tpu.selectors import CODAHyperparams, make_coda
from coda_tpu.selectors import surrogate as sg


@pytest.fixture(scope="module")
def task():
    return make_synthetic_task(seed=0, H=8, N=64, C=5)


def _trees_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def _run(task, hp, iters=24, seeds=2):
    factory = (lambda preds: make_coda(preds, hp))
    return run_seeds_compiled(factory, task.preds, task.labels,
                              iters=iters, seeds=seeds)


# ---------------------------------------------------------------------------
# default-exact pins
# ---------------------------------------------------------------------------

def test_default_is_exact_bitwise(task):
    """eig_scorer='exact' (the default) is the identical program — and
    the exact-config state carries NO fit leaves, so pre-knob serve
    snapshots/checkpoints keep their leaf structure."""
    r_default = _run(task, CODAHyperparams(n_parallel=2))
    r_exact = _run(task, CODAHyperparams(eig_scorer="exact",
                                         n_parallel=2))
    assert _trees_equal(r_default, r_exact)
    sel = make_coda(task.preds, CODAHyperparams())
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    assert state.surrogate is None


def test_default_exact_every_selector(task):
    """Non-CODA selectors know nothing of the knob; their programs are
    untouched (smoke: they still run and emit scores)."""
    from coda_tpu.selectors import SELECTOR_FACTORIES

    for name in ("iid", "uncertainty", "model_picker"):
        fac = SELECTOR_FACTORIES[name]
        r = run_seeds_compiled(lambda p, _f=fac: _f(p), task.preds,
                               task.labels, iters=5, seeds=1)
        assert np.isfinite(np.asarray(r.cumulative_regret)).all()


def test_k_ge_n_is_exact_parity(task):
    """surrogate:k >= N refreshes every row through the exact chain —
    the whole trajectory is bitwise the exact scorer's (the ladder's
    parity idiom), which also pins the shortlist refresh's per-row float
    choreography against the full pass."""
    r_exact = _run(task, CODAHyperparams(n_parallel=2))
    r_par = _run(task, CODAHyperparams(eig_scorer="surrogate:64",
                                       n_parallel=2))
    assert _trees_equal(r_exact, r_par)


def test_parse_scorer_rejects_garbage():
    with pytest.raises(ValueError, match="unknown eig_scorer"):
        sg.parse_scorer("surrogate")
    with pytest.raises(ValueError, match="unknown eig_scorer"):
        sg.parse_scorer("surrogate:0")
    with pytest.raises(ValueError, match="unknown eig_scorer"):
        make_coda(make_synthetic_task(seed=0, H=4, N=16, C=3).preds,
                  CODAHyperparams(eig_scorer="nope"))


def test_surrogate_requires_incremental_tier(task):
    with pytest.raises(ValueError, match="incremental"):
        make_coda(task.preds, CODAHyperparams(eig_scorer="surrogate:8",
                                              eig_mode="factored"))
    with pytest.raises(ValueError, match="pallas"):
        make_coda(task.preds, CODAHyperparams(eig_scorer="surrogate:8",
                                              eig_backend="pallas"))


# ---------------------------------------------------------------------------
# the structural contract
# ---------------------------------------------------------------------------

def _drive(task, hp, rounds, seed=0):
    sel = make_coda(task.preds, hp)
    st = jax.jit(sel.init)(jax.random.PRNGKey(seed))
    upd = jax.jit(sel.update)
    slx = jax.jit(sel.select)
    key = jax.random.PRNGKey(seed + 1)
    for _ in range(rounds):
        key, k = jax.random.split(key)
        res = slx(st, k)
        st = upd(st, res.idx, task.labels[res.idx], res.prob)
    return sel, st, key


def test_selected_index_score_is_exact(task):
    """The shortlist-rows-are-exact property: on every round (warmup AND
    surrogate-scored), the index selection argmaxes carries the exact
    chain's score, never a raw prediction."""
    hp = CODAHyperparams(eig_scorer="surrogate:8")
    sel = make_coda(task.preds, hp)
    st = jax.jit(sel.init)(jax.random.PRNGKey(0))
    upd = jax.jit(sel.update)
    slx = jax.jit(sel.select)
    score_exact = jax.jit(sel.extras["score_exact"])
    key = jax.random.PRNGKey(1)
    surrogate_rounds = 0
    for _ in range(sg.SURROGATE_WARMUP_ROUNDS + 15):
        key, k = jax.random.split(key)
        res = slx(st, k)
        exact = np.asarray(score_exact(st))
        got = np.asarray(st.eig_scores_cached)
        i = int(res.idx)
        assert exact[i].tobytes() == got[i].tobytes()
        if (int(st.surrogate.rounds) > sg.SURROGATE_WARMUP_ROUNDS
                and not bool(st.surrogate.last_fallback)):
            surrogate_rounds += 1
            # ...and on surrogate rounds the vector genuinely differs
            # off-shortlist (this is not the exact pass in disguise)
        st = upd(st, res.idx, task.labels[res.idx], res.prob)
    assert surrogate_rounds > 0, "the surrogate never carried a round"


def test_forced_violation_falls_back_bitwise(task):
    """Corrupting the fit weights makes the gate trip and the round run
    the FULL exact pass: the produced score vector is bitwise the exact
    config's round, and the fallback is counted + flagged."""
    hp = CODAHyperparams(eig_scorer="surrogate:8")
    sel, st, key = _drive(task, hp, sg.SURROGATE_WARMUP_ROUNDS + 3)
    assert int(st.surrogate.rounds) > sg.SURROGATE_WARMUP_ROUNDS
    # corrupt the solved weights: predictions become garbage, so the
    # escape/audit/delta gate must trip on the next update
    bad_fit = st.surrogate._replace(
        w=jnp.full_like(st.surrogate.w, 1e3))
    st_bad = st._replace(surrogate=bad_fit)
    upd = jax.jit(sel.update)
    slx = jax.jit(sel.select)
    key, k = jax.random.split(key)
    res = slx(st_bad, k)
    fb0 = int(st_bad.surrogate.fallbacks)
    st_after = upd(st_bad, res.idx, task.labels[res.idx], res.prob)
    assert bool(st_after.surrogate.last_fallback)
    assert int(st_after.surrogate.fallbacks) == fb0 + 1
    # the fallback round's scores are bitwise the exact scorer's
    exact_scores = np.asarray(
        jax.jit(sel.extras["score_exact"])(st_after))
    got = np.asarray(st_after.eig_scores_cached)
    assert exact_scores.tobytes() == got.tobytes()


def test_fallback_rate_and_margin_counters(task):
    """Healthy run: warmup rounds are never counted as fallbacks, the
    fit refolds every round, and the margin gauge is finite once the
    surrogate scores rounds."""
    hp = CODAHyperparams(eig_scorer="surrogate:16")
    _, st, _ = _drive(task, hp, sg.SURROGATE_WARMUP_ROUNDS + 10)
    fit = st.surrogate
    assert int(fit.rounds) == sg.SURROGATE_WARMUP_ROUNDS + 10
    assert int(fit.fits) == int(fit.rounds)
    assert int(fit.fallbacks) <= 10  # never counts warmup
    assert np.isfinite(float(fit.margin))


# ---------------------------------------------------------------------------
# real-digits regret envelope
# ---------------------------------------------------------------------------

def test_digits_100_round_regret_envelope():
    """The acceptance trace: 100 labels of real digits under the
    surrogate stay inside the committed envelope of the exact scorer's
    label-weighted cumulative regret."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    from check_perf import (
        SURROGATE_ENVELOPE_ABS,
        SURROGATE_ENVELOPE_RATIO,
    )

    from coda_tpu.data import Dataset, find_task_file

    fp = find_task_file(os.path.join(os.path.dirname(__file__), "..",
                                     "data"), "digits")
    ds = Dataset.from_file(fp, name="digits")
    exact = _run(ds, CODAHyperparams(n_parallel=2), iters=100, seeds=2)
    surr = _run(ds, CODAHyperparams(eig_scorer="surrogate:32",
                                    n_parallel=2), iters=100, seeds=2)
    ce = float(np.asarray(exact.cumulative_regret)[:, -1].mean())
    cs = float(np.asarray(surr.cumulative_regret)[:, -1].mean())
    assert cs <= SURROGATE_ENVELOPE_RATIO * ce + SURROGATE_ENVELOPE_ABS, \
        f"surrogate digits regret {cs} outside envelope of exact {ce}"


# ---------------------------------------------------------------------------
# composition: q-wide, sparse tier
# ---------------------------------------------------------------------------

def test_sparse_tier_composition(task):
    """surrogate:k >= N composed with the sparse tier is bitwise the
    sparse tier's exact run (the parity rung composes within the
    representation — dense-vs-sparse itself is the PR 9 contract, not
    this one); and a truncated sparse:K surrogate run stays finite with
    the fit carried on the sparse state."""
    r_sparse = _run(task, CODAHyperparams(posterior="sparse:8",
                                          n_parallel=2))
    r_both = _run(task, CODAHyperparams(eig_scorer="surrogate:64",
                                        posterior="sparse:8",
                                        n_parallel=2))
    assert _trees_equal(r_sparse, r_both)  # K=8 >= C=5, k=64 >= N=64
    hp = CODAHyperparams(eig_scorer="surrogate:8", posterior="sparse:3")
    sel, st, _ = _drive(task, hp, sg.SURROGATE_WARMUP_ROUNDS + 4)
    assert st.sparse is not None and st.dirichlets is None
    assert np.isfinite(np.asarray(st.eig_scores_cached)).all()
    assert int(st.surrogate.rounds) == sg.SURROGATE_WARMUP_ROUNDS + 4


def test_q_wide_composition(task):
    """--acq-batch q drives select_q (re-ranking the surrogate-produced
    hybrid vector unchanged) and the fused update_q (one multi-row
    refresh + one surrogate pass per round): the q-wide surrogate run
    stays inside the envelope of the q-wide exact run at the same label
    budget, and the fit counters advance per ROUND."""
    iters, q = 10, 4
    r_exact = run_seeds_compiled(
        lambda p: make_coda(p, CODAHyperparams(n_parallel=1)),
        task.preds, task.labels, iters=iters, seeds=1, acq_batch=q)
    r_surr = run_seeds_compiled(
        lambda p: make_coda(p, CODAHyperparams(
            eig_scorer="surrogate:16", n_parallel=1)),
        task.preds, task.labels, iters=iters, seeds=1, acq_batch=q)
    ce = float(np.asarray(r_exact.cumulative_regret)[0, -1])
    cs = float(np.asarray(r_surr.cumulative_regret)[0, -1])
    assert cs <= 1.5 * ce + 1.0  # the batchq envelope class
    # fused update_q threads the fit: counters advance once per round
    sel = make_coda(task.preds, CODAHyperparams(
        eig_scorer="surrogate:16", n_parallel=1))
    from coda_tpu.selectors.batch import resolve_batch_fns

    sel_q, upd_q = resolve_batch_fns(sel, q)
    st = jax.jit(sel.init)(jax.random.PRNGKey(0))
    res = jax.jit(lambda s, k: sel_q(s, k))(st, jax.random.PRNGKey(1))
    st2 = jax.jit(upd_q)(st, res.idx, task.labels[res.idx], res.prob)
    assert int(st2.surrogate.rounds) == 1
    assert int(st2.surrogate.fits) == 1


# ---------------------------------------------------------------------------
# resolver budget boundary
# ---------------------------------------------------------------------------

def test_resolver_charges_scorer_tier():
    """The auto eig_mode budget prices the scorer: the C=1000 x H=2000
    HF-pool shape at N=256 (sparse:32) exceeds the incremental budget
    under the exact scorer's full-stream pricing but resolves to the
    cheap tier under the surrogate — pinned BOTH ways, like the PR 9
    posterior term."""
    from coda_tpu.selectors.coda import resolve_eig_mode

    H, N, C = 2000, 256, 1000
    assert resolve_eig_mode(
        CODAHyperparams(posterior="sparse:32"), H, N, C) == "rowscan"
    assert resolve_eig_mode(
        CODAHyperparams(posterior="sparse:32",
                        eig_scorer="surrogate:64"), H, N, C) \
        == "incremental"
    # the existing pins must not have moved (PR 9's boundary)
    assert resolve_eig_mode(
        CODAHyperparams(posterior="sparse:32"), 2000, 64, C) \
        == "incremental"
    assert resolve_eig_mode(CODAHyperparams(), 500, 256, C) \
        == "incremental"


# ---------------------------------------------------------------------------
# recorder / replay integration
# ---------------------------------------------------------------------------

def test_record_v3_carries_fallback_stream(task, tmp_path):
    """New records are v3 with the per-round surrogate_fallback array
    (all-False for exact scorers), schema-valid, and bitwise
    self-replayable."""
    import os
    import sys

    from coda_tpu.engine.loop import run_seeds_recorded
    from coda_tpu.engine.replay import verify_replay
    from coda_tpu.telemetry.recorder import (
        KNOB_FIELDS,
        RECORD_SCHEMA_VERSION,
        RunRecord,
        environment_fingerprint,
    )

    assert "eig_scorer" in KNOB_FIELDS
    assert RECORD_SCHEMA_VERSION >= 3
    hp = CODAHyperparams(eig_scorer="surrogate:8", n_parallel=2)
    factory = (lambda preds: make_coda(preds, hp))
    result, aux = run_seeds_recorded(
        factory, task.preds, task.labels,
        iters=sg.SURROGATE_WARMUP_ROUNDS + 6, seeds=2, trace_k=4)
    fp = environment_fingerprint(
        dataset=task, knobs={"method": "coda", "loss": "acc",
                             "eig_scorer": "surrogate:8",
                             "n_parallel": 2})
    record = RunRecord.from_result(result, aux, fp,
                                   run={"task": task.name, "iters":
                                        sg.SURROGATE_WARMUP_ROUNDS + 6,
                                        "seeds": 2, "method": "coda",
                                        "loss": "acc"})
    rec_dir = tmp_path / "surrogate_rec"
    record.save(str(rec_dir))
    fb = record.arrays["surrogate_fallback"]
    assert fb.dtype.kind == "b" and fb.shape == (
        2, sg.SURROGATE_WARMUP_ROUNDS + 6)
    # schema checker accepts the v3 layout
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    from check_record_schema import check_record

    assert check_record(str(rec_dir)) == []
    # bitwise self-replay through the identical program
    report = verify_replay(record, factory, task.preds, task.labels,
                           loss="acc", score_tol=0.0)
    assert report.parity


def test_against_exact_triages_as_scorer_envelope(task):
    """compare_records routes a surrogate-vs-exact knob diff through the
    regret-envelope triage (classification eig-scorer-envelope) instead
    of reporting a fake bitwise divergence."""
    from coda_tpu.engine.loop import run_seeds_recorded
    from coda_tpu.engine.replay import compare_records
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    iters = sg.SURROGATE_WARMUP_ROUNDS + 8

    def rec(scorer):
        hp = CODAHyperparams(eig_scorer=scorer, n_parallel=1)
        result, aux = run_seeds_recorded(
            lambda preds: make_coda(preds, hp), task.preds, task.labels,
            iters=iters, seeds=1, trace_k=4)
        fp = environment_fingerprint(
            dataset=task, knobs={"method": "coda", "eig_scorer": scorer})
        return RunRecord.from_result(
            result, aux, fp, run={"task": task.name, "iters": iters,
                                  "seeds": 1, "method": "coda",
                                  "loss": "acc"})

    a, b = rec("exact"), rec("surrogate:8")
    report = compare_records(a, b)
    assert report.seeds[0].classification == "eig-scorer-envelope"
    env = report.meta["scorer_envelope"]
    assert env["scorer_a"] == "exact"
    assert env["scorer_b"] == "surrogate:8"
    assert "eig_scorer" in report.meta["knob_diff"]
    # same-scorer records still compare through the bitwise path
    report2 = compare_records(a, rec("exact"))
    assert report2.parity


# ---------------------------------------------------------------------------
# serving
# ---------------------------------------------------------------------------

def test_serve_bucket_and_export_import_roundtrip(task):
    """A surrogate-spec bucket warms/compiles, serves labels, surfaces
    the surrogate counters on /stats + lint-clean /metrics, and a
    session export/import round-trips the fit state BITWISE."""
    from coda_tpu.serve import SelectorSpec, ServeApp
    from coda_tpu.telemetry import prometheus

    def mk():
        app = ServeApp(capacity=2, max_wait=0.001,
                       spec=SelectorSpec.create(
                           "coda", n_parallel=2,
                           eig_scorer="surrogate:8"))
        app.add_task("tiny", task.preds)
        app.start()
        return app

    labels = np.asarray(task.labels)
    app = mk()
    try:
        out = app.open_session()
        sid = out["session"]
        for _ in range(6):
            out = app.label(sid, int(labels[out["idx"]]),
                            idx=out["idx"])
        snap = app.stats()
        assert snap["surrogate_rounds"] >= 6
        assert snap["surrogate_fit_refreshes"] >= 6
        assert snap["buckets"][0]["surrogate"]["rounds"] >= 6
        text = prometheus.render(app.telemetry.registry,
                                 serve_metrics=app.metrics)
        assert prometheus.lint(text) == []
        # gauges, not _total counters: live-slot sums may decrease when
        # sessions close/demote/migrate away
        assert "coda_serve_surrogate_rounds " in text or \
            "coda_serve_surrogate_rounds{" in text
        assert "coda_serve_surrogate_rounds_total" not in text
        payload = app.export_session(sid, close=True)
        app2 = mk()
        try:
            info = app2.import_session(payload)
            assert info.get("restored_via") == "snapshot"
            payload2 = app2.export_session(sid)
            assert [c["data"] for c in payload["carries"]] == \
                [c["data"] for c in payload2["carries"]]
            assert [tuple(c["shape"]) for c in payload["carries"]] == \
                [tuple(c["shape"]) for c in payload2["carries"]]
            res = app2.label(sid, int(labels[out["idx"]]),
                             idx=out["idx"])
            assert res["n_labeled"] == 7
        finally:
            app2.drain(timeout=5.0)
    finally:
        app.drain(timeout=5.0)


def test_exact_server_has_no_surrogate_families(task):
    """Exact-scorer servers carry NO surrogate keys/families — absent,
    not zero (the families only exist where the rung runs)."""
    from coda_tpu.serve import SelectorSpec, ServeApp
    from coda_tpu.telemetry import prometheus

    app = ServeApp(capacity=2, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=2))
    app.add_task("tiny", task.preds)
    app.start()
    try:
        out = app.open_session()
        app.label(out["session"],
                  int(np.asarray(task.labels)[out["idx"]]),
                  idx=out["idx"])
        snap = app.stats()
        assert "surrogate_rounds" not in snap
        assert snap["buckets"][0]["surrogate"] is None
        text = prometheus.render(app.telemetry.registry,
                                 serve_metrics=app.metrics)
        assert "surrogate" not in text
        assert prometheus.lint(text) == []
    finally:
        app.drain(timeout=5.0)
