"""The eig_entropy='approx' fast-entropy scoring path.

Contract under test (the ISSUE-2 opt-in numerics bar):

  * ``log2_approx`` holds max |Δlog2| <= 1e-5 over the whole clamped
    entropy domain [1e-12, 1] (measured ~6.9e-6: degree-6 mantissa
    polynomial, fit error 5.1e-6, plus fp32 evaluation noise);
  * EIG scores from the approx lowering hold the COMMITTED bound
    max |Δscore| <= 1e-4 vs the exact path (measured ~2e-5 at worst
    over adversarial caches; |Δscore| <= 2·max|Δlog2| analytically,
    since each mixture row sums to ~1 over models and the pi_xi class
    weights sum to 1);
  * the jnp and pallas approx lowerings agree with each other as
    tightly as the exact pair (same polynomial, same reduction order),
    so auto backend routing never changes numerics class;
  * a >=30-round selection trace on the committed REAL digits task is
    IDENTICAL to the default path's (argmax ordering survives the
    perturbation);
  * the default stays byte-identical (existing parity tests cover it;
    the guards here pin the knob's error surface).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

_DIGITS = os.path.join(os.path.dirname(__file__), "..", "data",
                       "digits.npz")


def _random_cache(key, N, C, H, floor_frac=0.0):
    """Random normalized cache tensors; ``floor_frac`` of the hyp entries
    are zeroed so the scoring clamp engages exactly at the 1e-12 floor —
    the edge of the approx domain."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    rows = jax.random.uniform(k1, (C, H)) + 0.1
    rows /= rows.sum(-1, keepdims=True)
    hyp = jax.random.uniform(k2, (C, N, H)) + 0.01
    if floor_frac:
        mask = jax.random.uniform(k4, hyp.shape) < floor_frac
        hyp = jnp.where(mask, 0.0, hyp)
    hyp /= jnp.clip(hyp.sum(-1, keepdims=True), 1e-30, None)
    pi_xi = jax.random.uniform(k3, (N, C))
    pi_xi /= pi_xi.sum(-1, keepdims=True)
    pi = pi_xi.mean(0)
    return rows, hyp, pi / pi.sum(), pi_xi


def test_log2_approx_bound_on_clamped_domain():
    """max |Δlog2| <= 1e-5 over [1e-12, 1] — log-uniform + linear sweeps
    + the exact floor/ceiling endpoints."""
    from coda_tpu.ops.masked import log2_approx

    rng = np.random.default_rng(0)
    xs = np.concatenate([
        10.0 ** rng.uniform(-12, 0, 200_000),
        np.linspace(1e-12, 1.0, 200_000),
        [1e-12, 1.0, 0.5, 2 ** -40],
    ]).astype(np.float32)
    xs = np.clip(xs, 1e-12, 1.0)
    got = np.asarray(jax.jit(log2_approx)(jnp.asarray(xs)), np.float64)
    want = np.log2(xs.astype(np.float64))
    assert np.max(np.abs(got - want)) <= 1e-5


def test_entropy2_approx_bound():
    """|ΔH| of simplex rows is bounded by max |Δlog2| (errors scale with
    Σp = 1); exact mode stays the default and untouched."""
    from coda_tpu.ops.masked import entropy2

    rng = np.random.default_rng(1)
    p = rng.dirichlet(np.full(1000, 0.1), size=500).astype(np.float32)
    p = jnp.asarray(p)
    h_ex = np.asarray(entropy2(p), np.float64)
    h_ap = np.asarray(entropy2(p, approx=True), np.float64)
    assert np.max(np.abs(h_ex - h_ap)) <= 1e-5
    # the default signature is unchanged exact math
    np.testing.assert_array_equal(np.asarray(entropy2(p)),
                                  np.asarray(entropy2(p, approx=False)))


def test_eig_scores_approx_committed_bound():
    """THE committed accuracy bound: max |Δscore| <= 1e-4 between the
    exact and approx lowerings of the incremental scoring pass, over
    caches that include floor-clamped (zero-probability) entries."""
    from coda_tpu.selectors.coda import eig_scores_from_cache

    worst = 0.0
    for seed, (N, C, H, frac) in enumerate(
            [(300, 5, 12, 0.0), (257, 4, 40, 0.3), (96, 10, 100, 0.1)]):
        rows, hyp, pi, pi_xi = _random_cache(
            jax.random.PRNGKey(seed), N, C, H, floor_frac=frac)
        ex = np.asarray(eig_scores_from_cache(rows, hyp, pi, pi_xi,
                                              chunk=64))
        ap = np.asarray(eig_scores_from_cache(rows, hyp, pi, pi_xi,
                                              chunk=64, approx=True))
        worst = max(worst, float(np.max(np.abs(ex - ap))))
        assert int(ex.argmax()) == int(ap.argmax())
    assert worst <= 1e-4, worst


def test_factored_and_rowscan_approx_bound():
    """The non-incremental jnp tiers carry the same knob and the same
    bound (auto tier fallback must not change numerics class)."""
    from coda_tpu.ops.confusion import (
        create_confusion_matrices,
        ensemble_preds,
        initialize_dirichlets,
    )
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.selectors.coda import (
        eig_scores_factored,
        eig_scores_rowscan,
        update_pi_hat,
    )

    t = make_synthetic_task(seed=2, H=8, N=96, C=5)
    preds = t.preds
    hard = preds.argmax(-1).T.astype(jnp.int32)
    ens = ensemble_preds(preds).argmax(-1)
    dirichlets = 2.0 * initialize_dirichlets(
        create_confusion_matrices(ens, preds, mode="soft"), 0.1, False)
    pi_xi, pi = update_pi_hat(dirichlets, preds)
    for fn in (eig_scores_factored, eig_scores_rowscan):
        ex = np.asarray(fn(dirichlets, pi, pi_xi, hard, num_points=64,
                           chunk=32))
        ap = np.asarray(fn(dirichlets, pi, pi_xi, hard, num_points=64,
                           chunk=32, approx=True))
        assert np.max(np.abs(ex - ap)) <= 1e-4
        assert int(ex.argmax()) == int(ap.argmax())


def test_pallas_approx_matches_jnp_approx():
    """The two lowerings of the SAME polynomial chain agree like the
    exact pair does — including a ragged final block."""
    from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas
    from coda_tpu.selectors.coda import eig_scores_from_cache

    for seed, (N, C, H, blk) in enumerate([(300, 5, 12, 64), (77, 4, 9, 32)]):
        rows, hyp, pi, pi_xi = _random_cache(
            jax.random.PRNGKey(10 + seed), N, C, H, floor_frac=0.2)
        ref = np.asarray(eig_scores_from_cache(rows, hyp, pi, pi_xi,
                                               chunk=blk, approx=True))
        pal = np.asarray(eig_scores_cache_pallas(
            rows, hyp, pi, pi_xi, block=blk, interpret=True, approx=True))
        np.testing.assert_allclose(ref, pal, rtol=1e-4, atol=1e-6)
        assert int(ref.argmax()) == int(pal.argmax())


def test_refresh_kernel_approx_matches_dus_then_score():
    """The fused refresh+score kernel under approx == DUS the row, then
    jnp-approx score; the returned cache is unaffected by the entropy
    flavor (entropy only shapes scores)."""
    from coda_tpu.ops.pallas_eig import eig_scores_refresh_pallas
    from coda_tpu.selectors.coda import eig_scores_from_cache

    N, C, H = 200, 7, 11
    rows, hyp, pi, pi_xi = _random_cache(jax.random.PRNGKey(3), N, C, H)
    hyp_t = jax.random.uniform(jax.random.PRNGKey(4), (N, H)) + 0.1
    hyp_t /= hyp_t.sum(-1, keepdims=True)
    c = jnp.int32(2)
    hyp_ref = hyp.at[c].set(hyp_t)
    ref = np.asarray(eig_scores_from_cache(rows, hyp_ref, pi, pi_xi,
                                           chunk=48, approx=True))
    scores, hyp_out = eig_scores_refresh_pallas(
        rows, hyp, hyp_t, c, pi, pi_xi, block=48, interpret=True,
        approx=True)
    np.testing.assert_allclose(ref, np.asarray(scores), rtol=1e-4,
                               atol=1e-6)
    np.testing.assert_array_equal(np.asarray(hyp_ref), np.asarray(hyp_out))


def test_fused_compute_kernel_approx():
    """eig_refresh='fused' composes with eig_entropy='approx': the
    in-kernel row computation is entropy-flavor-independent, the scoring
    tail follows the knob."""
    from coda_tpu.ops.beta import dirichlet_to_beta
    from coda_tpu.ops.pallas_eig import eig_scores_refresh_compute_pallas
    from coda_tpu.ops.pbest import compute_pbest
    from coda_tpu.selectors.coda import (
        eig_scores_from_cache,
        update_eig_cache_parts,
    )

    N, C, H = 77, 4, 10
    dir_ = jax.random.uniform(jax.random.PRNGKey(5), (H, C, C)) * 3.0 + 0.5
    hard = jax.random.randint(jax.random.PRNGKey(6), (N, H), 0,
                              C).astype(jnp.int32)
    a_cc, b_cc = dirichlet_to_beta(dir_)
    c = jnp.int32(1)
    a_t, b_t = a_cc[:, c], b_cc[:, c]
    rows = compute_pbest(a_cc.T, b_cc.T).at[c].set(compute_pbest(a_t, b_t))
    rows2, hyp, pi, pi_xi = _random_cache(jax.random.PRNGKey(7), N, C, H)
    del rows2
    _, hyp_t_ref = update_eig_cache_parts(dir_, c, hard)
    s_ref = np.asarray(eig_scores_from_cache(
        rows, hyp.at[c].set(hyp_t_ref), pi, pi_xi, chunk=32, approx=True))
    s_fc, hyp_fc = eig_scores_refresh_compute_pallas(
        rows, hyp, a_t, b_t, hard, c, pi, pi_xi, block=32, interpret=True,
        approx=True)
    # the in-kernel dots carry the fused-compute tolerance (measured
    # 2.34e-4 on silicon); the approx entropy adds its own <=1e-4
    np.testing.assert_allclose(s_ref, np.asarray(s_fc), rtol=1e-3,
                               atol=2e-5)


def test_batched_and_vmapped_approx_dispatch():
    """vmapped approx callers ride the batched kernels (and the jnp
    fallback) with the approx flag intact — per-element parity with the
    jnp approx composition."""
    from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas
    from coda_tpu.selectors.coda import eig_scores_from_cache

    B = 3
    keys = jax.random.split(jax.random.PRNGKey(8), B)
    packs = [_random_cache(k, 64, 4, 10) for k in keys]
    rows = jnp.stack([p[0] for p in packs])
    hyp = jnp.stack([p[1] for p in packs])
    pi = jnp.stack([p[2] for p in packs])
    pi_xi = jnp.stack([p[3] for p in packs])
    out = jax.vmap(
        lambda r, h, p, px: eig_scores_cache_pallas(
            r, h, p, px, block=32, approx=True)
    )(rows, hyp, pi, pi_xi)
    ref = jax.vmap(
        lambda r, h, p, px: eig_scores_from_cache(
            r, h, p, px, chunk=32, approx=True)
    )(rows, hyp, pi, pi_xi)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-6)


@pytest.mark.skipif(not os.path.exists(_DIGITS),
                    reason="committed digits task not present")
def test_approx_real_digits_trace_parity():
    """THE committed trace-parity bar: >=30 rounds on the REAL digits
    task, eig_entropy='approx' (jnp lowering) vs the byte-identical
    default — identical selection trace and best-model readout."""
    from coda_tpu.data import Dataset
    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda

    ds = Dataset.from_file(_DIGITS)
    r_def = run_experiment(
        make_coda(ds.preds, CODAHyperparams(eig_mode="incremental")),
        ds, iters=30, seed=0)
    r_apx = run_experiment(
        make_coda(ds.preds, CODAHyperparams(eig_mode="incremental",
                                            eig_entropy="approx")),
        ds, iters=30, seed=0)
    np.testing.assert_array_equal(np.asarray(r_def.chosen_idx),
                                  np.asarray(r_apx.chosen_idx))
    np.testing.assert_array_equal(np.asarray(r_def.best_model),
                                  np.asarray(r_apx.best_model))


@pytest.mark.skipif(not os.path.exists(_DIGITS),
                    reason="committed digits task not present")
def test_approx_pallas_real_digits_trace_parity():
    """Same bar through the PALLAS lowering (interpret mode here; the
    identical kernels Mosaic-compile on silicon): approx + pallas
    reproduces the default trace on the real digits task."""
    from coda_tpu.data import Dataset
    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda

    ds = Dataset.from_file(_DIGITS)
    r_def = run_experiment(
        make_coda(ds.preds, CODAHyperparams(eig_mode="incremental")),
        ds, iters=30, seed=0)
    r_apx = run_experiment(
        make_coda(ds.preds, CODAHyperparams(
            eig_mode="incremental", eig_backend="pallas",
            eig_entropy="approx")),
        ds, iters=30, seed=0)
    np.testing.assert_array_equal(np.asarray(r_def.chosen_idx),
                                  np.asarray(r_apx.chosen_idx))
    np.testing.assert_array_equal(np.asarray(r_def.best_model),
                                  np.asarray(r_apx.best_model))


def test_eig_entropy_guards():
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.selectors import CODAHyperparams, make_coda

    t = make_synthetic_task(seed=1, H=4, N=32, C=4)
    with pytest.raises(ValueError, match="unknown eig_entropy"):
        make_coda(t.preds, CODAHyperparams(eig_entropy="Approx"))
    # the direct tier is the reference-choreography cross-check: exact only
    with pytest.raises(ValueError, match="exact entropy"):
        make_coda(t.preds, CODAHyperparams(eig_mode="direct",
                                           eig_entropy="approx"))


def test_cli_eig_entropy_plumbs_to_selector():
    """--eig-entropy reaches CODAHyperparams through the CLI factory (and
    therefore through the suite's method_args, which set the same attr)."""
    from coda_tpu.cli import build_selector_factory, parse_args
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine import run_experiment

    t = make_synthetic_task(seed=3, H=5, N=48, C=4)
    args = parse_args(["--synthetic", "5,48,4", "--method", "coda",
                       "--eig-entropy", "approx", "--eig-chunk", "48"])
    sel = build_selector_factory(args, "synthetic")(t.preds)
    assert sel.hyperparams["eig_entropy"] == "approx"
    # and the selector runs end to end with the approx scoring pass
    res = run_experiment(sel, t, iters=5, seed=0)
    assert np.isfinite(np.asarray(res.regret)).all()


def test_suite_warm_profile_schema():
    """SuiteRunner emits per-method AND per-family warm steady-state
    seconds; a second pass over the same runner is all-warm (cold
    attribution persists with the jit cache) and its warm profile
    accounts every pair."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner

    loaders = [
        lambda i=i: make_synthetic_task(seed=i, H=4, N=64, C=4,
                                        name=f"fam_{i}")
        for i in range(2)
    ] + [lambda: make_synthetic_task(seed=9, H=4, N=32, C=4,
                                     name="other_0")]
    runner = SuiteRunner(iters=3, seeds=2)
    runner.run(loaders, ["iid"], progress=lambda *_: None)
    cold_stats = runner.last_stats
    assert set(cold_stats["per_method_warm_s"]) <= {"iid"}
    # warm rerun: every pair is compile-free, so the profile covers all
    # 3 tasks across both families
    runner.run(loaders, ["iid"], progress=lambda *_: None)
    warm_stats = runner.last_stats
    assert all(not p["cold"] for p in warm_stats["pairs"])
    assert set(warm_stats["per_family_warm_s"]) == {"fam", "other"}
    # the profile rounds to milliseconds; compare at that granularity
    assert warm_stats["per_method_warm_s"]["iid"] == pytest.approx(
        sum(p["seconds"] for p in warm_stats["pairs"]), abs=5e-3)


def test_bench_suite_baseline_ratio():
    """vs_baseline populates from the committed CPU capture exactly when
    the run measured the baseline's sweep (full families, all methods,
    5 seeds x 100 iters), preferring steady-state compute."""
    import argparse
    import importlib
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    bs = importlib.import_module("scripts.bench_suite")

    def mkargs(**kw):
        base = dict(small=False, methods=bs._DEFAULT_METHODS, seeds=5,
                    iters=100)
        base.update(kw)
        return argparse.Namespace(**base)

    line = {"value": 200.0, "steady_state_compute_s": 100.0,
            "vs_baseline": 0.0}
    bs._baseline_ratio(line, mkargs())
    assert line["vs_baseline"] == pytest.approx(9501.6 / 100.0, rel=1e-3)
    assert "steady_state" in line["vs_baseline_source"]

    # no steady-state capture -> the cold value, labeled as such
    line2 = {"value": 200.0, "vs_baseline": 0.0}
    bs._baseline_ratio(line2, mkargs())
    assert line2["vs_baseline"] == pytest.approx(9501.6 / 200.0, rel=1e-3)
    assert "cold" in line2["vs_baseline_source"]

    # non-comparable configs keep the 0.0 sentinel
    for bad in (mkargs(small=True), mkargs(methods="iid"),
                mkargs(seeds=3), mkargs(iters=10)):
        line3 = {"value": 200.0, "vs_baseline": 0.0}
        bs._baseline_ratio(line3, bad)
        assert line3["vs_baseline"] == 0.0
    # the median-of-reps profile helper: missing keys count as 0.0
    med = bs._median_profile([{"a": 1.0, "b": 2.0}, {"a": 3.0}])
    assert med == {"a": 2.0, "b": 1.0}
