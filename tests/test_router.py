"""Replicated-fleet tests: rendezvous invariants, migration parity, peer
paging, merged observability (``serve/router.py`` + ``serve/fleet.py``).

The load-bearing claims: (1) rendezvous hashing is STABLE — adding one of
N replicas re-owns only ~1/N of a 10k-id keyspace, and every moved id
moves TO the new replica — and DETERMINISTIC across processes (keyed
digest, never Python's salted ``hash``); (2) a session routed through the
fleet and force-migrated mid-trajectory is BITWISE the session that ran
on one replica the whole time (the migration rides the digest-verified
export/import path); (3) a rolling restart of every replica in sequence
drops nothing and double-applies nothing; (4) fleet observability is one
endpoint — merged ``/stats`` and a lint-clean per-replica-labeled
``/metrics`` — not a per-replica curl loop.
"""

from __future__ import annotations

import json
import subprocess
import sys

import numpy as np
import pytest

H, N, C = 4, 48, 4
_ROW_KEYS = ("next_idx", "next_prob", "best", "pbest_max", "pbest_entropy")


@pytest.fixture(scope="module")
def task():
    from coda_tpu.data import make_synthetic_task

    return make_synthetic_task(seed=0, H=H, N=N, C=C)


def _factory(task, capacity=4, **kw):
    from coda_tpu.serve import SelectorSpec, ServeApp

    def make(rid):
        app = ServeApp(capacity=capacity, max_wait=0.001,
                       spec=SelectorSpec.create("coda",
                                                n_parallel=capacity),
                       **kw)
        app.add_task(task.name, task.preds)
        return app

    return make


def _fleet(task, n=2, warm=True, **kw):
    from coda_tpu.serve import Fleet

    return Fleet(_factory(task, **kw), n_replicas=n).start(warm=warm)


def _assert_rows_bitwise(a, b, what=""):
    for k in _ROW_KEYS:
        va, vb = a[k], b[k]
        if isinstance(va, float):
            assert np.float32(va).tobytes() == np.float32(vb).tobytes(), \
                (what, k, va, vb)
        else:
            assert va == vb, (what, k, va, vb)


# ---------------------------------------------------------------------------
# rendezvous hashing invariants
# ---------------------------------------------------------------------------

def test_rendezvous_stability_10k_keyspace():
    """Adding a replica to {r0,r1,r2} re-owns ~1/4 of 10k session ids,
    and EVERY re-owned id moves to the new replica — the minimal-movement
    property drain-and-migrate relies on."""
    from coda_tpu.serve import rendezvous_owner

    ids = [f"{i:032x}" for i in range(10_000)]
    before = {sid: rendezvous_owner(sid, ["r0", "r1", "r2"]) for sid in ids}
    after = {sid: rendezvous_owner(sid, ["r0", "r1", "r2", "r3"])
             for sid in ids}
    moved = [sid for sid in ids if before[sid] != after[sid]]
    frac = len(moved) / len(ids)
    assert 0.15 < frac < 0.35, frac          # ~1/4, not a reshuffle
    assert all(after[sid] == "r3" for sid in moved)  # only TO the newcomer
    # removal is the mirror image: dropping r3 sends its ids back to
    # exactly where they were (everyone else never moved)
    assert all(rendezvous_owner(sid, ["r0", "r1", "r2"]) == before[sid]
               for sid in ids[:1000])
    # and the spread over 3 replicas is roughly even (each within 2x)
    from collections import Counter

    counts = Counter(before.values())
    assert len(counts) == 3
    assert max(counts.values()) < 2 * min(counts.values()), counts


def test_rendezvous_deterministic_across_processes():
    """Owners must agree between processes: the hash is a keyed digest,
    not Python's per-process-salted ``hash``. A subprocess computes the
    same owners for the same ids."""
    from coda_tpu.serve import rendezvous_owner, rendezvous_rank

    ids = [f"{i:08x}" for i in range(200)]
    replicas = ["alpha", "beta", "gamma"]
    mine = {sid: rendezvous_owner(sid, replicas) for sid in ids}
    code = (
        "import json, sys\n"
        "from coda_tpu.serve import rendezvous_owner\n"
        f"ids = {ids!r}\n"
        f"reps = {replicas!r}\n"
        "print(json.dumps({s: rendezvous_owner(s, reps) for s in ids}))\n"
    )
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, check=True,
                         env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:"
                              "/bin:/usr/local/bin", "PYTHONPATH": "."})
    theirs = json.loads(out.stdout.strip().splitlines()[-1])
    assert theirs == mine
    # rank order is total (ties broken by id): permutation-invariant
    assert rendezvous_rank(ids[0], replicas) == \
        rendezvous_rank(ids[0], list(reversed(replicas)))


# ---------------------------------------------------------------------------
# router-vs-direct bitwise trajectory parity through a forced migration
# ---------------------------------------------------------------------------

def test_router_migration_bitwise_vs_direct(task):
    """A session driven through the router and FORCE-MIGRATED to the
    other replica mid-trajectory finishes bitwise identical — rows and
    recorder stream — to the same seed driven on a single direct app.
    The migration must be digest-verified (snapshot or replay), and the
    router must keep answering under the session's original id."""
    fleet = _fleet(task, n=2)
    r = fleet.router
    direct_app = _factory(task)("direct")
    direct_app.start(warm=True)
    try:
        out = r.open_session(seed=7)
        sid = out["session"]
        for _ in range(3):
            out = r.label(sid, int(out["idx"]) % C)
        src = r._locate(sid)
        dst = [rid for rid in fleet.replica_ids if rid != src][0]
        info = r.migrate_session(sid, src, dst)
        assert info.get("migrated") == sid, info
        assert info["via"] in ("snapshot", "replay")  # digest-verified
        assert r.counters["migrations"] == 1
        assert sum(r.migrations_via.values()) == 1
        # the session now answers from the destination, same id
        assert fleet.apps[dst].store.alive(sid) or \
            fleet.apps[dst].tiers.parked(sid)
        assert not fleet.apps[src].store.alive(sid)
        for _ in range(3):
            out = r.label(sid, int(out["idx"]) % C)
        assert out["n_labeled"] == 6

        ctrl = direct_app.open_session(seed=7)
        for _ in range(6):
            ctrl = direct_app.label(ctrl["session"], int(ctrl["idx"]) % C)
        last = {k: fleet.apps[dst].store.get(sid).last[k]
                for k in _ROW_KEYS}
        ctrl_last = {k: direct_app.store.get(ctrl["session"]).last[k]
                     for k in _ROW_KEYS}
        _assert_rows_bitwise(last, ctrl_last, "migrated vs direct")
        rows_m = fleet.apps[dst].recorder.history(sid)
        rows_c = direct_app.recorder.history(ctrl["session"])
        assert len(rows_m) == len(rows_c) == 7  # open + 6 labels
        for rm, rc in zip(rows_m, rows_c):
            for k in _ROW_KEYS:
                assert rm[k] == rc[k], k
    finally:
        direct_app.drain(timeout=10)
        fleet.drain(timeout=10)


# ---------------------------------------------------------------------------
# rolling restart: every replica in sequence, zero drops
# ---------------------------------------------------------------------------

def test_rolling_restart_zero_drop(task):
    """Open sessions across a 3-replica fleet, rolling-restart every
    replica, keep labeling: no session is dropped, no label double-
    applies (the n_labeled sentinel), every migration digest-verified."""
    fleet = _fleet(task, n=3)
    r = fleet.router
    try:
        outs = {}
        for i in range(6):
            out = r.open_session(seed=i)
            outs[out["session"]] = out
        for sid, out in outs.items():
            outs[sid] = r.label(sid, int(out["idx"]) % C)
        report = fleet.rolling_restart()
        assert report["replicas_restarted"] == 3
        assert report["sessions_dropped"] == 0
        assert report["migration_failures"] == 0
        assert report["migrations"] > 0
        assert sum(report["migrations_via"].values()) == \
            report["migrations"]
        for sid, out in outs.items():
            out = r.label(sid, int(out["idx"]) % C)
            assert out["n_labeled"] == 2, (sid, out)  # exactly-once
    finally:
        fleet.drain(timeout=10)


# ---------------------------------------------------------------------------
# health-driven eviction and rejoin
# ---------------------------------------------------------------------------

def test_health_eviction_and_rejoin(task):
    """A replica whose /healthz stops reporting ready leaves the routing
    set — but only after ``health_hysteresis`` CONSECUTIVE bad probes (a
    single flapping poll must not churn the HRW keyspace); recovery
    rejoins it symmetrically."""
    fleet = _fleet(task, n=2, warm=False)
    r = fleet.router
    try:
        r.stop()  # drive health checks by hand, no poller races
        app0 = fleet.apps["r0"]
        app0.ready.clear()   # simulate a replica stuck compiling
        statuses = r.check_health()
        assert statuses["r0"] == "unready"
        # ONE bad probe is a flap, not an eviction (hysteresis = 2)
        assert r.routable() == ["r0", "r1"]
        # ...and a recovery inside the window resets the streak
        app0.ready.set()
        r.check_health()
        assert r.routable() == ["r0", "r1"]
        assert r.counters["evictions"] == 0
        app0.ready.clear()
        r.check_health()
        statuses = r.check_health()   # second consecutive bad: evict
        assert statuses["r0"] == "unready"
        assert r.routable() == ["r1"]
        hz = r.healthz()
        assert hz["status"] == "degraded" and hz["ready"]
        for i in range(4):   # everything routes to the survivor
            out = r.open_session(seed=i)
            assert fleet.apps["r1"].store.alive(out["session"])
        app0.ready.set()
        r.check_health()
        statuses = r.check_health()   # second consecutive good: rejoin
        assert statuses["r0"] in ("ok", "degraded")
        assert r.routable() == ["r0", "r1"]
        assert r.counters["evictions"] == 1
        assert r.counters["rejoins"] == 1
    finally:
        fleet.drain(timeout=10)


# ---------------------------------------------------------------------------
# demotion-aware peer paging
# ---------------------------------------------------------------------------

def test_peer_paging_moves_warm_session_to_peer(task):
    """A warm session offered to the fleet pager lands on the less-loaded
    peer (digest-verified import), the router re-points the sid, and a
    later label serves from the peer — the trajectory unbroken."""
    fleet = _fleet(task, n=2)
    r = fleet.router
    try:
        out = r.open_session(seed=3)
        sid = out["session"]
        for _ in range(2):
            out = r.label(sid, int(out["idx"]) % C)
        src = r._locate(sid)
        dst = [rid for rid in fleet.replica_ids if rid != src][0]
        app_src = fleet.apps[src]
        assert app_src.tiers.try_demote(sid)      # hot -> warm
        assert app_src.tiers.page_to_peer(sid)    # warm -> the peer
        assert app_src.metrics.peer_pages == 1
        assert r._placed[sid] == dst
        assert r.counters["peer_pages"] == 1
        assert fleet.peer_pages == 1
        assert not app_src.store.alive(sid)
        assert not app_src.tiers.parked(sid)
        out = r.label(sid, int(out["idx"]) % C)   # served by the peer
        assert out["n_labeled"] == 3
        assert fleet.apps[dst].store.alive(sid)
    finally:
        fleet.drain(timeout=10)


def test_peer_paging_failure_reparks_warm(task):
    """A pager that refuses (no peer / import failure) must leave the
    session warm and reachable — paging can degrade, never lose."""
    fleet = _fleet(task, n=2)
    try:
        out = fleet.router.open_session(seed=1)
        sid = out["session"]
        out = fleet.router.label(sid, int(out["idx"]) % C)
        src = fleet.router._locate(sid)
        app = fleet.apps[src]
        assert app.tiers.try_demote(sid)
        app.tiers.page_out = lambda s, p: False   # every peer refuses
        assert app.tiers.page_to_peer(sid) is False
        assert app.tiers.parked(sid)              # still warm, reachable
        out = fleet.router.label(sid, int(out["idx"]) % C)  # wakes locally
        assert out["n_labeled"] == 2
    finally:
        fleet.drain(timeout=10)


# ---------------------------------------------------------------------------
# merged observability: one /stats, one lint-clean /metrics
# ---------------------------------------------------------------------------

def test_fleet_merged_stats_and_metrics(task):
    from coda_tpu.telemetry.prometheus import lint

    fleet = _fleet(task, n=2)
    r = fleet.router
    try:
        sids = []
        for i in range(4):
            out = r.open_session(seed=i)
            sids.append(out["session"])
            r.label(out["session"], int(out["idx"]) % C)
        st = r.stats()
        assert set(st["replicas"]) == {"r0", "r1"}
        assert st["aggregate"]["open_sessions"] == 4
        assert st["aggregate"]["requests"] >= 8
        assert st["router"]["counters"]["requests_routed"] >= 8
        assert set(st["router"]["requests_to"]) == {"r0", "r1"}
        text = r.render_metrics()
        assert lint(text) == []
        # per-replica labels on the serve families, each family ONCE
        assert 'coda_serve_requests_total{replica="r0"}' in text
        assert 'coda_serve_requests_total{replica="r1"}' in text
        assert text.count("# TYPE coda_serve_requests_total counter") == 1
        assert "coda_router_requests_routed_total" in text
        assert "coda_router_requests_to_replica_total" in text
    finally:
        fleet.drain(timeout=10)


def test_multiprocess_http_fleet_smoke(task):
    """The real multi-process fleet: 2 serve replicas as SUBPROCESSES
    behind the router via HttpReplica — open → label → migrate (the
    hold/fence protocol over real HTTP) → label → close, with the
    migrated trajectory BITWISE identical to the same seed driven on a
    single in-process app. Also pins the per-verb deadlines that retired
    the old fixed 60 s blanket timeout."""
    import os
    import re
    import subprocess
    import sys
    import time as _time
    import urllib.request

    from coda_tpu.serve import HttpReplica, SessionRouter, VERB_DEADLINES

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs, urls = [], {}
    try:
        for rid in ("h0", "h1"):
            p = subprocess.Popen(
                [sys.executable, "-u", "-m", "coda_tpu.cli", "serve",
                 "--synthetic", f"{H},{N},{C}", "--port", "0",
                 "--capacity", "4", "--no-warm"],
                cwd=repo, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            procs.append(p)
            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline:
                line = p.stdout.readline()
                m = re.search(r"http://127\.0\.0\.1:(\d+)/", line or "")
                if m:
                    urls[rid] = f"http://127.0.0.1:{m.group(1)}"
                    break
                if p.poll() is not None:
                    raise RuntimeError(f"replica {rid} died at startup")
            assert rid in urls, "replica never announced its port"
        for url in urls.values():   # wait out readiness over real HTTP
            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(url + "/healthz",
                                                timeout=2):
                        break
                except Exception:
                    _time.sleep(0.2)
        replicas = {rid: HttpReplica(rid, url)
                    for rid, url in urls.items()}
        # the satellite's claim: per-verb deadlines, not one blanket 60 s
        t = replicas["h0"].transport
        assert t.deadline("healthz") == VERB_DEADLINES["healthz"] < 60
        assert t.deadline("import") == VERB_DEADLINES["import"] > 60
        r = SessionRouter(replicas)
        out = r.open_session(seed=7)
        sid = out["session"]
        for _ in range(3):
            out = r.label(sid, int(out["idx"]) % C)
        src = r._locate(sid)
        dst = [x for x in urls if x != src][0]
        info = r.migrate_session(sid, src, dst)
        assert info.get("migrated") == sid, info
        assert info["via"] in ("snapshot", "replay")
        assert info["epoch"] == 1
        assert not replicas[src].has_session(sid)   # fenced over HTTP
        for _ in range(3):
            out = r.label(sid, int(out["idx"]) % C)
        assert out["n_labeled"] == 6
        rows_fleet = r.trace(sid)["rounds"]
        r.close_session(sid)

        ctrl = _factory(task)("direct")
        ctrl.start(warm=False)
        try:
            o = ctrl.open_session(seed=7)
            for _ in range(6):
                o = ctrl.label(o["session"], int(o["idx"]) % C)
            rows_ctrl = ctrl.recorder.history(o["session"])
        finally:
            ctrl.drain(timeout=10)
        assert len(rows_fleet) == len(rows_ctrl) == 7
        for rf, rc in zip(rows_fleet, rows_ctrl):
            _assert_rows_bitwise(rf, rc, "http fleet vs direct")
        r.drain()
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=10)


def test_router_http_front_door(task):
    """The router serves over the SAME AsyncHTTPServer as a replica:
    open/label/close + merged /stats + /healthz + /metrics over real
    HTTP, and the open lands on the rendezvous owner of the minted id."""
    import threading
    import urllib.request

    from coda_tpu.serve import make_server

    fleet = _fleet(task, n=2)
    srv = make_server(fleet.router, 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"

    def req(method, path, body=None):
        data = None if body is None else json.dumps(body).encode()
        rq = urllib.request.Request(base + path, data=data, method=method)
        with urllib.request.urlopen(rq, timeout=30) as resp:
            return resp.status, resp.read()

    try:
        code, body = req("POST", "/session", {"seed": 0})
        out = json.loads(body)
        sid = out["session"]
        code, body = req("POST", f"/session/{sid}/label",
                         {"label": int(out["idx"]) % C})
        assert code == 200 and json.loads(body)["n_labeled"] == 1
        code, body = req("GET", "/stats")
        st = json.loads(body)
        assert st["role"] == "router" and "aggregate" in st
        code, body = req("GET", "/healthz")
        assert code == 200 and json.loads(body)["role"] == "router"
        code, body = req("GET", "/metrics")
        assert b'replica="r0"' in body or b'replica="r1"' in body
        code, body = req("DELETE", f"/session/{sid}")
        assert code == 200
    finally:
        srv.shutdown()
        srv.server_close()
        fleet.drain(timeout=10)
