"""Task-parallel suite scheduler: bitwise parity of scheduled vs serial
``run_batched`` on the 8-virtual-device CPU mesh, LPT planning, memory-aware
placement, and resume-under-placement (``coda_tpu/engine/scheduler.py``).

Placement must be a pure copy: the scheduler runs the SAME executables with
the SAME seed keys on other devices, so every result is pinned bitwise
(``tobytes`` equality, not allclose) against the serial path.
"""

from __future__ import annotations

import os

import numpy as np
import pytest


def _families():
    from coda_tpu.data import make_synthetic_task

    fam_a = [make_synthetic_task(seed=i, H=4, N=40, C=3, name=f"alpha_{i}")
             for i in range(3)]
    fam_b = [make_synthetic_task(seed=10 + i, H=3, N=24, C=4,
                                 name=f"beta_{i}") for i in range(2)]
    return [fam_a, fam_b]


# mixed deterministic (uncertainty) / stochastic (iid, model_picker — the
# latter also exercising the runtime-traced per-task ε argument)
_METHODS = ["iid", "uncertainty", "model_picker"]


def _assert_bitwise(r_a: dict, r_b: dict) -> None:
    assert set(r_a) == set(r_b)
    for key in r_a:
        for fa, fb in zip(r_a[key], r_b[key]):
            fa, fb = np.asarray(fa), np.asarray(fb)
            assert fa.dtype == fb.dtype and fa.shape == fb.shape, key
            assert fa.tobytes() == fb.tobytes(), (
                f"{key}: scheduled result differs bitwise from serial")


def test_plan_schedule_lpt():
    """LPT: descending-cost dispatch order, each chunk onto the currently
    least-loaded device (ties -> lowest index / input order)."""
    from coda_tpu.engine.scheduler import plan_schedule

    costs = [5.0, 1.0, 4.0, 2.0, 3.0]
    order, assignment, loads = plan_schedule(costs, 2, "lpt")
    assert order == [0, 2, 4, 3, 1]
    # 5->d0; 4->d1; 3->d1(4<5); 2->d0(5<7); 1->d0 (tie 7,7 -> lowest index)
    assert assignment == [0, 0, 1, 0, 1]
    assert loads == [8.0, 7.0]
    # fifo keeps input order with the same least-loaded placement
    order_f, assignment_f, _ = plan_schedule(costs, 2, "fifo")
    assert order_f == [0, 1, 2, 3, 4]
    assert assignment_f == [0, 1, 1, 0, 1]
    with pytest.raises(ValueError, match="unknown schedule"):
        plan_schedule(costs, 2, "bogus")


def test_estimate_cost_profile_normalization():
    """Family totals are normalized by this run's family task counts (the
    profile sums over tasks), method weights redistribute around mean 1,
    and unseen families fall back to the mean known per-task rate."""
    from coda_tpu.engine.scheduler import estimate_cost

    profile = {"per_family_warm_s": {"domainnet": 120.0, "glue": 7.0},
               "per_method_warm_s": {"coda": 30.0, "iid": 10.0}}
    counts = {"domainnet": 12, "glue": 7}
    # per-task rates: domainnet 10, glue 1; method weights: coda 1.5, iid .5
    assert estimate_cost("domainnet", "coda", 2, profile, counts) \
        == pytest.approx(10.0 * 1.5 * 2)
    assert estimate_cost("glue", "iid", 7, profile, counts) \
        == pytest.approx(1.0 * 0.5 * 7)
    # unseen family -> mean of known rates (5.5); unseen method -> weight 1
    assert estimate_cost("msv", "vma", 1, profile, counts) \
        == pytest.approx(5.5)
    # no profile at all -> uniform per-task weights
    assert estimate_cost("msv", "vma", 3, None, None) == pytest.approx(3.0)


def test_plan_fleet_schedule_weighted():
    """Host-level placement: weighted least-normalized-load greedy —
    a 2x-capacity host absorbs ~2x the work; with unit weights the plan
    reduces exactly to plan_schedule's device-level assignment."""
    from coda_tpu.engine.scheduler import (
        partition_hosts,
        plan_fleet_schedule,
        plan_schedule,
    )

    costs = [5.0, 1.0, 4.0, 2.0, 3.0]
    # unit weights == plan_schedule
    order_f, assign_f, loads_f = plan_fleet_schedule(costs, [1, 1], "lpt")
    order_d, assign_d, loads_d = plan_schedule(costs, 2, "lpt")
    assert (order_f, assign_f, loads_f) == (order_d, assign_d, loads_d)
    # a host with 3 devices takes ~3x the load of a 1-device host
    _, assign, loads = plan_fleet_schedule(costs, [3, 1], "lpt")
    assert loads[0] > loads[1]
    assert loads[0] == pytest.approx(sum(costs) - loads[1])
    assert loads[1] <= sum(costs) / 3
    with pytest.raises(ValueError, match="positive"):
        plan_fleet_schedule(costs, [1, 0])
    with pytest.raises(ValueError, match="unknown schedule"):
        plan_fleet_schedule(costs, [1, 1], "bogus")
    # host partitioning: near-equal contiguous groups, validated specs
    assert partition_hosts(8, 3) == [[0, 1, 2], [3, 4, 5], [6, 7]]
    assert partition_hosts(4, [[0, 1], [2, 3]]) == [[0, 1], [2, 3]]
    with pytest.raises(ValueError, match="hosts"):
        partition_hosts(2, 3)
    with pytest.raises(ValueError, match="disjoint"):
        partition_hosts(4, [[0, 1], [1, 2]])
    with pytest.raises(ValueError, match="cover"):
        partition_hosts(4, [[0], [2]])  # non-covering spec would crash
        #                                 the flat device-indexed plan


def test_plan_two_level_composes_to_flat_devices():
    """Two-level placement flattens to a device assignment the existing
    compute loop executes unchanged: every chunk lands on a device of its
    host, host loads follow the fleet plan."""
    from coda_tpu.engine.scheduler import plan_fleet_schedule, plan_two_level

    costs = [7.0, 5.0, 4.0, 3.0, 2.0, 1.0]
    groups = [[0, 1], [2, 3, 4]]
    order, assignment, loads = plan_two_level(costs, groups, "lpt")
    _, h_assign, h_loads = plan_fleet_schedule(costs, [2, 3], "lpt")
    for i, d in enumerate(assignment):
        assert d in groups[h_assign[i]]
    assert len(loads) == 5
    for hi, g in enumerate(groups):
        assert sum(loads[d] for d in g) == pytest.approx(h_loads[hi])


def test_hosts_two_level_matches_serial_bitwise():
    """Fleet-host placement is still a pure copy: run_batched with
    hosts=2 over 4 devices reproduces the serial results BITWISE, and
    last_stats records the host groups + per-host load."""
    import jax

    from coda_tpu.engine.suite import SuiteRunner

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 (virtual) devices")
    groups = _families()
    r_ser = SuiteRunner(iters=3, seeds=3).run_batched(
        groups, ["iid", "uncertainty"], progress=lambda s: None)
    runner = SuiteRunner(iters=3, seeds=3)
    r_two = runner.run_batched(
        groups, ["iid", "uncertainty"], progress=lambda s: None,
        devices=4, hosts=2,
        cost_profile={"per_family_warm_s": {"alpha": 3.0, "beta": 1.0}})
    _assert_bitwise(r_ser, r_two)
    stats = runner.last_stats
    assert len(stats["hosts"]) == 2
    assert [len(g) for g in stats["hosts"]] == [2, 2]
    assert len(stats["host_load"]) == 2
    assert all(v >= 0 for v in stats["host_load"])


def test_resolve_devices():
    import jax

    from coda_tpu.engine.scheduler import resolve_devices

    local = jax.local_devices()
    assert resolve_devices("auto") == local
    assert resolve_devices(None) == local
    assert resolve_devices(2) == local[:2]
    assert resolve_devices("3") == local[:3]
    assert resolve_devices([local[1].id, local[0]]) == [local[1], local[0]]
    with pytest.raises(ValueError, match="local devices"):
        resolve_devices(len(local) + 1)


def test_scheduled_matches_serial_bitwise():
    """Scheduled placement over all 8 virtual devices must reproduce the
    serial run_batched results BITWISE for a mixed deterministic/stochastic
    multi-family config — same executables, same keys; placement is a pure
    copy. batch_caps marks model_picker memory-heavy, exercising the
    chunk-split + never-two-heavy-co-resident path under placement too."""
    import jax

    from coda_tpu.engine.suite import SuiteRunner

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    groups = _families()
    caps = {"model_picker": 2}
    r_ser = SuiteRunner(iters=3, seeds=3).run_batched(
        groups, _METHODS, batch_caps=caps, progress=lambda s: None)
    runner = SuiteRunner(iters=3, seeds=3)
    r_sch = runner.run_batched(
        groups, _METHODS, batch_caps=caps, progress=lambda s: None,
        devices="auto",
        cost_profile={"per_family_warm_s": {"alpha": 3.0, "beta": 1.0}})
    _assert_bitwise(r_ser, r_sch)
    stats = runner.last_stats
    assert stats["n_devices"] == len(jax.devices())
    assert stats["schedule"] == "lpt"
    # concurrency accounting: both totals recorded (they exceed each other
    # only through real concurrency / host gaps respectively, so no fixed
    # order is asserted — this host may serialize its virtual devices)
    assert stats["compute_s"] > 0 and stats["compute_device_s"] > 0
    assert set(stats["occupancy"]) == {d.id for d in jax.devices()}
    assert all(0.0 <= v <= 1.0 + 1e-6 for v in stats["occupancy"].values())
    # every pair record carries its placement
    assert all("device" in p for p in stats["pairs"])
    # model_picker chunks were split by the cap (memory-heavy valve)
    mp = [p["batched"] for p in stats["pairs"]
          if p["method"] == "model_picker"]
    assert mp and max(mp) <= 2


def test_scheduled_lpt_dispatch_order():
    """Given a synthetic cost profile, chunks must be DISPATCHED in
    descending estimated-cost order (the LPT ordering the plan promises):
    launch timestamps in the device timeline are monotone in cost."""
    import jax

    from coda_tpu.engine.suite import SuiteRunner

    runner = SuiteRunner(iters=2, seeds=2)
    runner.run_batched(
        _families(), ["iid", "uncertainty"], progress=lambda s: None,
        devices=min(2, len(jax.devices())),
        cost_profile={"per_family_warm_s": {"alpha": 50.0, "beta": 1.0},
                      "per_method_warm_s": {"iid": 3.0, "uncertainty": 1.0}})
    entries = [e for recs in runner.last_stats["device_timeline"].values()
               for e in recs]
    assert len(entries) == 4  # 2 families x 2 methods
    by_start = sorted(entries, key=lambda e: e["start"])
    costs = [e["est_cost"] for e in by_start]
    assert costs == sorted(costs, reverse=True), costs
    # the profile ranks alpha/iid first: 50/3 per task * 1.5 weight * 3 tasks
    assert by_start[0]["method"] == "iid"
    assert by_start[0]["tasks"][0].startswith("alpha")


def test_scheduled_resume_with_store(tmp_path):
    """DB-checked resume under placement: pairs finished by a SERIAL run
    are skipped by the scheduled rerun, the remainder completes, and the
    combined results match a serial force-rerun bitwise."""
    import jax

    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.tracking import TrackingStore

    groups = _families()
    store = TrackingStore(str(tmp_path / "s.sqlite"))
    # serial first pass finishes ONE method everywhere
    SuiteRunner(iters=2, seeds=2).run_batched(
        groups, ["uncertainty"], store=store, progress=lambda s: None)
    msgs: list = []
    runner = SuiteRunner(iters=2, seeds=2)
    r_sch = runner.run_batched(
        groups, ["uncertainty", "iid"], store=store, progress=msgs.append,
        devices="auto")
    # every uncertainty pair skipped, none dispatched under placement
    assert sum("skip" in m for m in msgs) == 5
    assert not any(p["method"] == "uncertainty"
                   for p in runner.last_stats["pairs"])
    assert set(r_sch) == {(f"alpha_{i}", "iid") for i in range(3)} \
        | {(f"beta_{i}", "iid") for i in range(2)}
    r_ref = SuiteRunner(iters=2, seeds=2).run_batched(
        groups, ["iid"], progress=lambda s: None)
    _assert_bitwise(r_ref, r_sch)
    # scheduled rerun now skips EVERYTHING (its own logs round-tripped)
    msgs.clear()
    out = runner.run_batched(groups, ["uncertainty", "iid"], store=store,
                             progress=msgs.append, devices="auto")
    assert out == {}
    assert sum("skip" in m for m in msgs) == 10
    store.close()


def test_scheduled_single_device_schema_and_parity():
    """devices=1 degenerates to a deferred-harvest pipeline on one device:
    results stay bitwise-serial and last_stats carries the same schema as
    the multi-device path (so bench plumbing never branches)."""
    from coda_tpu.engine.suite import SuiteRunner

    groups = _families()
    r_ser = SuiteRunner(iters=2, seeds=2).run_batched(
        groups, ["iid", "uncertainty"], progress=lambda s: None)
    runner = SuiteRunner(iters=2, seeds=2)
    r_one = runner.run_batched(groups, ["iid", "uncertainty"],
                               progress=lambda s: None, devices=1)
    _assert_bitwise(r_ser, r_one)
    stats = runner.last_stats
    assert stats["n_devices"] == 1
    for key in ("total_s", "load_s", "compute_s", "compute_device_s",
                "pairs", "per_method_warm_s", "per_family_warm_s",
                "n_devices", "schedule", "device_timeline", "occupancy"):
        assert key in stats, key
    # serial path exposes the same schema (minus the per-device content)
    ser_runner = SuiteRunner(iters=2, seeds=2)
    ser_runner.run_batched(groups, ["iid"], progress=lambda s: None)
    for key in ("compute_s", "compute_device_s", "n_devices", "schedule",
                "device_timeline", "occupancy"):
        assert key in ser_runner.last_stats, key


def test_cli_suite_subcommand(tmp_path):
    """`python -m coda_tpu.cli suite ...` drives the sweep with the
    scheduler flags plumbed through to run_batched."""
    from coda_tpu import cli
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.tracking import TrackingStore

    npdir = tmp_path / "preds"
    npdir.mkdir()
    for i in range(2):
        t = make_synthetic_task(seed=i, H=4, N=30, C=3, name=f"t_{i}")
        np.savez(npdir / f"t_{i}.npz", preds=np.asarray(t.preds),
                 labels=np.asarray(t.labels))
    db = str(tmp_path / "db.sqlite")
    cli.main(["suite", "--pred-dir", str(npdir), "--db", db,
              "--methods", "iid", "--seeds", "2", "--iters", "2",
              "--suite-devices", "2", "--schedule", "lpt"])
    store = TrackingStore(db)
    (n,) = store.query("SELECT COUNT(*) FROM experiments")[0]
    assert n == 2
    store.close()
