"""Golden-trace parity vs. the PyTorch reference implementation.

Runs the reference package (pure Python + torch CPU, mounted read-only at
/root/reference) in-process on the same tiny synthetic task and compares,
selector by selector, the quantities that determine behavior:

  * CODA: Dirichlet init, pi-hat, P(best), EIG score vectors (lockstep on
    identical label sequences) and the full independent selection trace;
  * Uncertainty / VMA / ActiveTesting / ModelPicker / IID: acquisition
    scores, LURE risks, posteriors, risk estimates (lockstep).

This is SURVEY.md section 4(b): the reference has no tests of its own, so
statistical/trace parity against it *is* the integration test. Skipped when
the reference checkout is unavailable.
"""

from __future__ import annotations

import random
import sys

import numpy as np
import pytest

REF_PATH = "/root/reference"

torch = pytest.importorskip("torch")

try:
    sys.path.insert(0, REF_PATH)
    from coda.coda import CODA as RefCODA  # noqa: E402
    from coda.baselines.iid import IID as RefIID  # noqa: E402
    from coda.baselines.uncertainty import (  # noqa: E402
        Uncertainty as RefUncertainty,
        uncertainty as ref_uncertainty_scores,
    )
    from coda.baselines.activetesting import ActiveTesting as RefAT  # noqa: E402
    from coda.baselines.vma import VMA as RefVMA  # noqa: E402
    from coda.baselines.modelpicker import ModelPicker as RefMP  # noqa: E402
    from coda.options import LOSS_FNS as REF_LOSS_FNS  # noqa: E402

    HAVE_REF = True
except Exception:  # pragma: no cover
    HAVE_REF = False

pytestmark = pytest.mark.skipif(not HAVE_REF, reason="reference not available")


class RefDS:
    """Minimal stand-in for the reference Dataset (preds + labels on CPU)."""

    def __init__(self, task):
        self.preds = torch.from_numpy(np.asarray(task.preds)).float()
        self.labels = torch.from_numpy(np.asarray(task.labels)).long()
        self.device = self.preds.device


@pytest.fixture(scope="module")
def task():
    from coda_tpu.data import make_synthetic_task

    # C>=3 so the diag prior differs from uniform; small enough that the
    # reference's per-step Python loops stay fast
    return make_synthetic_task(seed=3, H=4, N=40, C=3)


@pytest.fixture(scope="module")
def ref_ds(task):
    return RefDS(task)


def _fresh_ref_coda(ref_ds, **kw):
    random.seed(0)
    torch.manual_seed(0)
    return RefCODA(ref_ds, **kw)


def _ours_coda(task, **kw):
    from coda_tpu.selectors import CODAHyperparams, make_coda

    hp = CODAHyperparams(**kw) if kw else CODAHyperparams()
    return make_coda(task.preds, hp)


# ---------------------------------------------------------------- CODA core


def test_coda_init_parity(task, ref_ds):
    import jax

    ref = _fresh_ref_coda(ref_ds)
    sel = _ours_coda(task)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))

    np.testing.assert_allclose(
        np.asarray(state.dirichlets), ref.dirichlets.numpy(), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(state.pi_hat_xi), ref.pi_hat_xi.numpy(), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(state.pi_hat), ref.pi_hat.numpy(), rtol=1e-5, atol=1e-6
    )


def test_coda_init_parity_ablation_no_diag(task, ref_ds):
    import jax

    ref = _fresh_ref_coda(ref_ds, disable_diag_prior=True)
    sel = _ours_coda(task, disable_diag_prior=True)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(state.dirichlets), ref.dirichlets.numpy(), rtol=1e-5, atol=1e-6
    )


def test_coda_pbest_parity(task, ref_ds):
    import jax

    ref = _fresh_ref_coda(ref_ds)
    sel = _ours_coda(task)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))

    ours = np.asarray(sel.extras["get_pbest"](state))
    theirs = ref.get_pbest().numpy().squeeze()
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-6)


def test_coda_lockstep_trace_parity(task, ref_ds):
    """Drive both implementations with the REFERENCE's label choices and
    compare EIG vectors, selections, posteriors and P(best) every round."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors.coda import eig_scores

    labels_np = np.asarray(task.labels)
    ref = _fresh_ref_coda(ref_ds)
    sel = _ours_coda(task)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    hard_preds = jnp.argmax(task.preds, -1).T.astype(jnp.int32)

    eig_jit = jax.jit(
        lambda s: eig_scores(
            s.dirichlets, s.pi_hat, s.pi_hat_xi, hard_preds, chunk=64
        )
    )
    update_jit = jax.jit(sel.update)

    for rnd in range(6):
        ref_q, ref_cand = ref.eig_batched()
        ref_q = ref_q.numpy()
        ours_q = np.asarray(eig_jit(state))[np.asarray(ref_cand)]

        np.testing.assert_allclose(ours_q, ref_q, rtol=5e-4, atol=1e-5,
                                   err_msg=f"EIG mismatch at round {rnd}")
        assert int(np.argmax(ours_q)) == int(np.argmax(ref_q)), rnd

        # drive both with the reference's greedy choice
        idx = int(ref_cand[int(np.argmax(ref_q))])
        tc = int(labels_np[idx])
        ref.add_label(idx, tc, float(ref_q.max()))
        state = update_jit(state, jnp.asarray(idx), jnp.asarray(tc),
                           jnp.asarray(0.0))

        np.testing.assert_allclose(
            np.asarray(state.dirichlets), ref.dirichlets.numpy(),
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(state.pi_hat), ref.pi_hat.numpy(), rtol=1e-5, atol=1e-6
        )
        ours_pbest = np.asarray(sel.extras["get_pbest"](state))
        np.testing.assert_allclose(ours_pbest, ref.get_pbest().numpy().squeeze(),
                                   rtol=1e-4, atol=1e-6)


def test_coda_factored_eig_lockstep_parity(task, ref_ds):
    """The MXU-factored EIG kernel (the production path at scale) must match
    the reference's EIG vectors in lockstep, same as the direct kernel."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors.coda import eig_scores_factored

    labels_np = np.asarray(task.labels)
    ref = _fresh_ref_coda(ref_ds)
    sel = _ours_coda(task)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    hard_preds = jnp.argmax(task.preds, -1).T.astype(jnp.int32)

    eig_jit = jax.jit(
        lambda s: eig_scores_factored(
            s.dirichlets, s.pi_hat, s.pi_hat_xi, hard_preds, chunk=16
        )
    )
    update_jit = jax.jit(sel.update)

    for rnd in range(4):
        ref_q, ref_cand = ref.eig_batched()
        ref_q = ref_q.numpy()
        ours_q = np.asarray(eig_jit(state))[np.asarray(ref_cand)]
        np.testing.assert_allclose(ours_q, ref_q, rtol=5e-4, atol=1e-5,
                                   err_msg=f"factored EIG mismatch @ {rnd}")
        assert int(np.argmax(ours_q)) == int(np.argmax(ref_q)), rnd

        idx = int(ref_cand[int(np.argmax(ref_q))])
        tc = int(labels_np[idx])
        ref.add_label(idx, tc, float(ref_q.max()))
        state = update_jit(state, jnp.asarray(idx), jnp.asarray(tc),
                           jnp.asarray(0.0))


def _independent_trace_parity(task, ref_ds, iters: int):
    """Run reference and ours independently; assert identical greedy
    selection + best-model traces (both must report tie-free runs)."""
    from coda_tpu.engine import run_experiment

    labels_np = np.asarray(task.labels)
    ref = _fresh_ref_coda(ref_ds)
    ref_idxs, ref_bests = [], []
    for _ in range(iters):
        idx, prob = ref.get_next_item_to_label()
        idx = int(idx)
        ref.add_label(idx, int(labels_np[idx]), prob)
        ref_idxs.append(idx)
        ref_bests.append(int(ref.get_best_model_prediction()))
    assert not ref.stochastic  # no ties: the greedy trace is deterministic

    sel = _ours_coda(task)
    res = run_experiment(sel, task, iters=iters, seed=0)
    assert not bool(res.stochastic)
    assert np.asarray(res.chosen_idx).tolist() == ref_idxs
    assert np.asarray(res.best_model).tolist() == ref_bests


def test_coda_independent_trace_parity(task, ref_ds):
    """Full independent runs must produce the same selection + best-model
    sequence (both greedy; the task has no EIG ties)."""
    _independent_trace_parity(task, ref_ds, iters=10)


def _lockstep_coda_trace(task, ref_ds, rounds: int, **kw):
    """Drive both implementations with the reference's label choices and
    compare Dirichlets / pi-hat / P(best) every round (shared by the C=3
    and binary-C tasks)."""
    import jax
    import jax.numpy as jnp

    labels_np = np.asarray(task.labels)
    ref = _fresh_ref_coda(ref_ds, **kw)
    sel = _ours_coda(task, **kw)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    update_jit = jax.jit(sel.update)
    select_jit = jax.jit(sel.select)

    for rnd in range(rounds):
        ref_idx, ref_prob = ref.get_next_item_to_label()
        res = select_jit(state, jax.random.PRNGKey(rnd))
        assert not bool(res.stochastic), f"unexpected tie at round {rnd}"
        assert not ref.stochastic
        assert int(res.idx) == int(ref_idx), f"selection differs at {rnd}"
        np.testing.assert_allclose(float(res.prob), float(ref_prob),
                                   rtol=5e-4, atol=1e-5)

        tc = int(labels_np[int(ref_idx)])
        ref.add_label(int(ref_idx), tc, float(ref_prob))
        state = update_jit(state, jnp.asarray(int(ref_idx)), jnp.asarray(tc),
                           jnp.asarray(0.0))
        np.testing.assert_allclose(
            np.asarray(state.dirichlets), ref.dirichlets.numpy(),
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(state.pi_hat), ref.pi_hat.numpy(), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(sel.extras["get_pbest"](state)),
            ref.get_pbest().numpy().squeeze(), rtol=1e-4, atol=1e-6,
        )


@pytest.fixture(scope="module")
def task_binary():
    from coda_tpu.data import make_synthetic_task

    # C=2: the diag prior's off-diagonal 1/(C-1) hits 1.0 and every Beta is
    # the whole Dirichlet row (the civilcomments/GLUE-shaped case)
    return make_synthetic_task(seed=5, H=4, N=30, C=2)


def test_coda_binary_task_lockstep_parity(task_binary):
    _lockstep_coda_trace(task_binary, RefDS(task_binary), rounds=5)


def test_coda_q_iid_ablation_parity(task, ref_ds):
    """Ablation q=iid (reference coda/coda.py:289-291): uniform scores over
    the prefiltered pool — always tied, so both sides flag stochastic; the
    uniform probability must agree, and belief updates stay in lockstep."""
    import jax
    import jax.numpy as jnp

    labels_np = np.asarray(task.labels)
    ref = _fresh_ref_coda(ref_ds, q="iid")
    sel = _ours_coda(task, q="iid")
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    select_jit = jax.jit(sel.select)
    update_jit = jax.jit(sel.update)

    for rnd in range(4):
        cand = ref._prefilter(ref.unlabeled_idxs) or ref.unlabeled_idxs
        ref_idx, ref_prob = ref.get_next_item_to_label()
        res = select_jit(state, jax.random.PRNGKey(rnd))
        assert ref.stochastic and bool(res.stochastic)
        np.testing.assert_allclose(float(res.prob), 1.0 / len(cand),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(ref_prob), 1.0 / len(cand),
                                   rtol=1e-6)
        assert int(res.idx) in cand

        tc = int(labels_np[int(ref_idx)])
        ref.add_label(int(ref_idx), tc, float(ref_prob))
        state = update_jit(state, jnp.asarray(int(ref_idx)), jnp.asarray(tc),
                           jnp.asarray(0.0))
        np.testing.assert_allclose(
            np.asarray(state.dirichlets), ref.dirichlets.numpy(),
            rtol=1e-5, atol=1e-6,
        )


def test_coda_q_uncertainty_ablation_parity(task, ref_ds):
    """Ablation q=uncertainty (reference coda/coda.py:292-295): committee
    disagreement scores over the prefiltered pool; tie-free on this task, so
    selections match exactly in lockstep."""
    import jax
    import jax.numpy as jnp

    labels_np = np.asarray(task.labels)
    ref = _fresh_ref_coda(ref_ds, q="uncertainty")
    sel = _ours_coda(task, q="uncertainty")
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    select_jit = jax.jit(sel.select)
    update_jit = jax.jit(sel.update)

    for rnd in range(4):
        ref_idx, ref_prob = ref.get_next_item_to_label()
        res = select_jit(state, jax.random.PRNGKey(rnd))
        assert not ref.stochastic and not bool(res.stochastic)
        assert int(res.idx) == int(ref_idx), rnd
        np.testing.assert_allclose(float(res.prob), float(ref_prob),
                                   rtol=1e-5, atol=1e-7)

        tc = int(labels_np[int(ref_idx)])
        ref.add_label(int(ref_idx), tc, float(ref_prob))
        state = update_jit(state, jnp.asarray(int(ref_idx)), jnp.asarray(tc),
                           jnp.asarray(0.0))
        np.testing.assert_allclose(
            np.asarray(state.dirichlets), ref.dirichlets.numpy(),
            rtol=1e-5, atol=1e-6,
        )


def _disagreement_pool(ref_ds) -> list[int]:
    maj, _ = torch.mode(ref_ds.preds.argmax(-1), dim=0)
    mask = (ref_ds.preds.argmax(-1) != maj).sum(0) > 0
    return [i for i in range(ref_ds.preds.shape[1]) if mask[i]]


def test_coda_prefilter_noop_lockstep_parity(task, ref_ds):
    """prefilter_n >= |disagreement pool|: neither side subsamples
    (reference coda/coda.py:220-224 requires len(idxs) > prefilter_n), so the
    full greedy EIG trace must match and stay deterministic."""
    pool = _disagreement_pool(ref_ds)
    assert 0 < len(pool) < task.preds.shape[1]
    _lockstep_coda_trace(task, ref_ds, rounds=4, prefilter_n=len(pool))


def test_coda_prefilter_subsample_stochastic_both_sides(task, ref_ds):
    """prefilter_n < |disagreement pool|: both sides randomly subsample the
    EIG pool, flag the run stochastic, and pick from the disagreement set."""
    import jax

    pool = _disagreement_pool(ref_ds)
    k = len(pool) - 2
    assert k >= 1
    ref = _fresh_ref_coda(ref_ds, prefilter_n=k)
    ref_idx, _ = ref.get_next_item_to_label()
    assert ref.stochastic
    assert int(ref_idx) in pool

    sel = _ours_coda(task, prefilter_n=k)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    res = jax.jit(sel.select)(state, jax.random.PRNGKey(0))
    assert bool(res.stochastic)
    assert int(res.idx) in pool


def test_coda_eig_tie_marks_stochastic_both_sides():
    """Exact EIG ties (duplicated points) must set the stochastic flag on
    both implementations (reference coda/coda.py:306-311 isclose tie-break)."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.data import make_synthetic_task

    base = make_synthetic_task(seed=7, H=3, N=2, C=3)
    preds = np.repeat(np.asarray(base.preds), 4, axis=1)      # (H, 8, C)
    labels = np.repeat(np.asarray(base.labels), 4)

    class DS:
        pass

    ds = DS()
    ds.preds = torch.from_numpy(preds).float()
    ds.labels = torch.from_numpy(labels).long()
    ds.device = ds.preds.device
    random.seed(0)
    torch.manual_seed(0)
    ref = RefCODA(ds)
    ref.get_next_item_to_label()
    assert ref.stochastic

    from coda_tpu.selectors import CODAHyperparams, make_coda

    sel = make_coda(jnp.asarray(preds), CODAHyperparams())
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    res = jax.jit(sel.select)(state, jax.random.PRNGKey(0))
    assert bool(res.stochastic)


# ------------------------------------------------------------- baselines


def test_uncertainty_scores_parity(task, ref_ds):
    from coda_tpu.selectors.uncertainty import uncertainty_scores

    all_idxs = list(range(task.preds.shape[1]))
    theirs = ref_uncertainty_scores(ref_ds.preds, all_idxs).numpy()
    ours = np.asarray(uncertainty_scores(task.preds))
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-7)


def test_iid_risk_lockstep_parity(task, ref_ds):
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors.iid import make_iid

    labels_np = np.asarray(task.labels)
    random.seed(0)
    ref = RefIID(ref_ds, REF_LOSS_FNS["acc"])
    sel = make_iid(task.preds)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    update_jit = jax.jit(sel.update)
    risk_jit = jax.jit(sel.extras["risk"])

    for idx in [3, 17, 29, 5, 11]:
        tc = int(labels_np[idx])
        ref.add_label(idx, tc)
        state = update_jit(state, jnp.asarray(idx), jnp.asarray(tc),
                           jnp.asarray(0.0))
        np.testing.assert_allclose(
            np.asarray(risk_jit(state)), ref.get_risk_estimates().numpy(),
            rtol=1e-6, atol=1e-7,
        )


def test_activetesting_lockstep_parity(task, ref_ds):
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors.activetesting import (
        make_activetesting,
        surrogate_expected_losses,
    )

    H, N, C = task.preds.shape
    labels_np = np.asarray(task.labels)
    random.seed(0)
    ref = RefAT(ref_ds, REF_LOSS_FNS["acc"])
    sel = make_activetesting(task.preds, budget=8)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    update_jit = jax.jit(sel.update)

    base_scores = np.asarray(surrogate_expected_losses(task.preds).sum(0))

    for step, idx in enumerate([7, 21, 33, 2, 18]):
        # both sides' selection probability of `idx`, normalized over the
        # current unlabeled set — must agree before we feed it to LURE
        unlabeled = np.asarray(state.unlabeled)
        ours_prob = base_scores[idx] / base_scores[unlabeled].sum()

        pi_y = ref.surrogate.get_preds()
        pred_classes = ref_ds.preds.argmax(dim=2)
        y_star = pi_y[torch.arange(N), pred_classes]
        ref_scores = (1 - y_star).sum(0)[ref.d_u_idxs]
        ref_scores = ref_scores / ref_scores.sum()
        ref_prob = float(ref_scores[ref.d_u_idxs.index(idx)])
        np.testing.assert_allclose(ours_prob, ref_prob, rtol=1e-5)

        tc = int(labels_np[idx])
        ref.add_label(idx, tc, ref_prob)
        state = update_jit(state, jnp.asarray(idx), jnp.asarray(tc),
                           jnp.asarray(ours_prob, jnp.float32))

        ours_risk, ours_var = (
            np.asarray(x) for x in sel.extras["lure_risks_and_vars"](state)
        )
        theirs_risk, theirs_var = (
            x.numpy() for x in ref.get_lure_risks_and_vars()
        )
        np.testing.assert_allclose(ours_risk, theirs_risk, rtol=1e-4,
                                   atol=1e-6, err_msg=f"LURE step {step}")
        if step > 0:  # reference variance is NaN (0/0 unbiased var) at M=1
            np.testing.assert_allclose(ours_var, theirs_var, rtol=1e-4,
                                       atol=1e-6,
                                       err_msg=f"LURE var step {step}")


def test_vma_scores_parity(task, ref_ds):
    from coda_tpu.selectors.vma import vma_scores

    theirs = _ref_vma_acquisition(ref_ds)
    ours = np.asarray(vma_scores(task.preds))
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-6)


def test_modelpicker_lockstep_parity(task, ref_ds):
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors.modelpicker import (
        expected_entropies,
        make_modelpicker,
    )

    H, N, C = task.preds.shape
    labels_np = np.asarray(task.labels)
    eps = 0.46
    ref = RefMP(ref_ds, epsilon=eps)
    sel = make_modelpicker(task.preds, epsilon=eps)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    update_jit = jax.jit(sel.update)
    hard_preds = jnp.argmax(task.preds, -1).T.astype(jnp.int32)

    for idx in [1, 14, 26, 38, 9]:
        preds_unlabeled = ref_ds.preds.argmax(dim=2).transpose(0, 1)[ref.d_u_idxs]
        theirs_ent = ref.compute_entropies(
            preds_unlabeled, ref.posterior, H, C, ref.gamma
        ).numpy()
        ours_ent = np.asarray(
            expected_entropies(hard_preds, state.posterior, sel_gamma(eps), C)
        )[np.asarray(ref.d_u_idxs)]
        np.testing.assert_allclose(ours_ent, theirs_ent, rtol=1e-5, atol=1e-6)

        tc = int(labels_np[idx])
        ref.add_label(idx, tc)
        state = update_jit(state, jnp.asarray(idx), jnp.asarray(tc),
                           jnp.asarray(0.0))
        np.testing.assert_allclose(
            np.asarray(state.posterior), ref.posterior.numpy(),
            rtol=1e-5, atol=1e-7,
        )
        assert (np.asarray(state.correct_counts)
                == ref.correct_counts.numpy()).all()


def sel_gamma(eps: float) -> float:
    return (1.0 - eps) / eps


# ------------------------------------------------------- real-data parity


@pytest.fixture(scope="module")
def digits_task():
    """Real-data slice (the committed digits tensor: 14 sklearn classifiers
    x NIST digit scans, see REAL_TASK.md). N is subset for the reference's
    per-round Python-loop speed; the slice keeps the real per-model error
    structure intact."""
    import os

    from coda_tpu.data import Dataset

    path = os.path.join(os.path.dirname(__file__), "..", "data", "digits.npz")
    if not os.path.exists(path):
        pytest.skip("digits.npz not committed")
    full = Dataset.from_file(path)
    return Dataset(preds=full.preds[:, :220, :], labels=full.labels[:220],
                   name="digits_sub")


def _ref_vma_acquisition(ref_ds):
    """The reference VMA acquisition reconstructed on the full point set
    (shared by the synthetic and real-data parity tests)."""
    H, N, _ = ref_ds.preds.shape
    random.seed(0)
    ref = RefVMA(ref_ds, REF_LOSS_FNS["acc"])
    pi_y = ref.surrogate.get_preds()
    pred_classes = ref_ds.preds.argmax(dim=2)
    cols = torch.arange(N).unsqueeze(0).expand(H, N)
    losses_all = 1.0 - pi_y[cols, pred_classes]
    diff = (losses_all.unsqueeze(0) - losses_all.unsqueeze(1)).abs()
    mask = torch.triu(torch.ones(H, H, dtype=torch.bool), diagonal=1)
    return diff[mask].sum(0).numpy()


def test_coda_real_digits_independent_trace_parity(digits_task):
    """Independent CODA runs on REAL data must agree with the reference
    trace — synthetic toys can't catch distribution-dependent divergence
    (peaked/flat posteriors, near-tie EIG structure)."""
    _independent_trace_parity(digits_task, RefDS(digits_task), iters=8)


def test_coda_real_binary_independent_trace_parity():
    """The C=2 edge (off-diag prior hits 1.0, every Beta is the whole
    Dirichlet row) on REAL data: the committed breast_cancer task."""
    import os

    from coda_tpu.data import Dataset

    path = os.path.join(os.path.dirname(__file__), "..", "data",
                        "breast_cancer.npz")
    if not os.path.exists(path):
        pytest.skip("breast_cancer.npz not committed")
    task = Dataset.from_file(path)
    _independent_trace_parity(task, RefDS(task), iters=8)


def test_coda_real_widepool_independent_trace_parity():
    """The H=80 pool on real scans (digits_h80, see REAL_TASK.md): the
    widest model axis in the real-task set — the per-model Beta structure
    and the P(best) mixture have 80 genuinely different components. N is
    subset for the reference's per-round Python-loop speed."""
    import os

    from coda_tpu.data import Dataset

    path = os.path.join(os.path.dirname(__file__), "..", "data",
                        "digits_h80.npz")
    if not os.path.exists(path):
        pytest.skip("digits_h80.npz not committed")
    full = Dataset.from_file(path)
    task = Dataset(preds=full.preds[:, :160, :], labels=full.labels[:160],
                   name="digits_h80_sub")
    _independent_trace_parity(task, RefDS(task), iters=8)


def test_coda_real_text_independent_trace_parity():
    """The C=5 document-type text task (pyfiles, the GLUE-shaped family
    member, see REAL_TASK.md): real TF-IDF text models produce flatter,
    more-correlated posteriors than the image pools."""
    import os

    from coda_tpu.data import Dataset

    path = os.path.join(os.path.dirname(__file__), "..", "data",
                        "pyfiles.npz")
    if not os.path.exists(path):
        pytest.skip("pyfiles.npz not committed")
    full = Dataset.from_file(path)
    task = Dataset(preds=full.preds[:, :220, :], labels=full.labels[:220],
                   name="pyfiles_sub")
    _independent_trace_parity(task, RefDS(task), iters=8)


def test_uncertainty_real_digits_scores_parity(digits_task):
    from coda_tpu.selectors.uncertainty import uncertainty_scores

    ref_ds = RefDS(digits_task)
    N = digits_task.preds.shape[1]
    theirs = ref_uncertainty_scores(ref_ds.preds, list(range(N))).numpy()
    ours = np.asarray(uncertainty_scores(digits_task.preds))
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-7)


def test_vma_real_digits_scores_parity(digits_task):
    from coda_tpu.selectors.vma import vma_scores

    theirs = _ref_vma_acquisition(RefDS(digits_task))
    np.testing.assert_allclose(np.asarray(vma_scores(digits_task.preds)),
                               theirs, rtol=1e-4, atol=1e-6)


def test_modelpicker_real_digits_lockstep_parity(digits_task):
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors.modelpicker import (
        expected_entropies,
        make_modelpicker,
    )

    ref_ds = RefDS(digits_task)
    H, N, C = digits_task.preds.shape
    eps = 0.46
    mp_ref = RefMP(ref_ds, epsilon=eps)
    sel = make_modelpicker(digits_task.preds, epsilon=eps)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    update_jit = jax.jit(sel.update)
    hard_preds = jnp.argmax(digits_task.preds, -1).T.astype(jnp.int32)
    labels_np = np.asarray(digits_task.labels)
    for idx in [3, 57, 120]:
        pred_u = ref_ds.preds.argmax(dim=2).transpose(0, 1)[mp_ref.d_u_idxs]
        theirs_ent = mp_ref.compute_entropies(
            pred_u, mp_ref.posterior, H, C, mp_ref.gamma).numpy()
        ours_ent = np.asarray(
            expected_entropies(hard_preds, state.posterior, sel_gamma(eps), C)
        )[np.asarray(mp_ref.d_u_idxs)]
        np.testing.assert_allclose(ours_ent, theirs_ent, rtol=1e-5, atol=1e-6)
        tc = int(labels_np[idx])
        mp_ref.add_label(idx, tc)
        state = update_jit(state, jnp.asarray(idx), jnp.asarray(tc),
                           jnp.asarray(0.0))
        np.testing.assert_allclose(np.asarray(state.posterior),
                                   mp_ref.posterior.numpy(),
                                   rtol=1e-5, atol=1e-7)
