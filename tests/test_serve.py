"""Tests for the batched multi-session serving layer (``coda_tpu/serve``).

The load-bearing claim: one compiled masked slab step serving many
concurrent sessions is EXACTLY the sequential single-session
``InteractiveSelector`` path, replayed in parallel — pinned bitwise on the
CPU backend (where the slab step resolves to the ``lax.map`` lowering; see
``make_slab_step``). Around it: slot lifecycle (reuse after close),
admission control (backpressure at a full slab, over real HTTP), the two
slab-step lowerings agreeing with each other, padded shape buckets never
proposing phantom items, metrics plumbing, and a smoke-scale closed-loop
load-generator run — the serving path is exercised on every PR.
"""

from __future__ import annotations

import http.client
import json
import threading

import numpy as np
import pytest


@pytest.fixture(scope="module")
def serve_task():
    from coda_tpu.data import make_synthetic_task

    return make_synthetic_task(seed=0, H=5, N=48, C=4)


def _drive_reference(selector, seed, labels, rounds):
    """The sequential single-session reference path: one
    ``InteractiveSelector``, driven select/best per processed request — the
    exact key choreography the slab step must reproduce. Returns the
    per-request (idx, prob, best) rows; ``labels`` maps idx -> class."""
    from coda_tpu.selectors.protocol import InteractiveSelector

    ref = InteractiveSelector(selector, seed=seed)
    rows = []
    idx, prob = ref.get_next_item_to_label()
    best = ref.get_best_model_prediction()
    rows.append((idx, prob, best))
    for _ in range(rounds):
        ref.add_label(idx, int(labels[idx]), prob)
        idx, prob = ref.get_next_item_to_label()
        best = ref.get_best_model_prediction()
        rows.append((idx, prob, best))
    return rows, ref


# ---------------------------------------------------------------------------
# parity: the acceptance-criterion test
# ---------------------------------------------------------------------------

def test_serve_batch_step_parity_coda(serve_task):
    """>= 16 concurrent sessions per single compiled dispatch, with every
    session's (idx, prob, best) results BITWISE-identical to its sequential
    InteractiveSelector replay (the acceptance criterion)."""
    import jax.numpy as jnp

    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.serve import SelectorSpec, SessionStore

    cap, rounds = 16, 4
    spec = SelectorSpec.create("coda", n_parallel=cap)
    store = SessionStore(capacity=cap)
    store.register_task("t", serve_task.preds)
    sessions = [store.open("t", spec, seed=s) for s in range(cap)]
    bucket = sessions[0].bucket
    labels = np.asarray(serve_task.labels)

    # batched path: one dispatch per round, all 16 sessions riding it
    served = {se.sid: [] for se in sessions}
    res = bucket.dispatch({se.slot: {"do_update": False}
                           for se in sessions})
    assert len(res) == cap  # one compiled step served all 16
    for se in sessions:
        se.last = res[se.slot]
        served[se.sid].append(res[se.slot])
    for _ in range(rounds):
        reqs = {
            se.slot: {"do_update": True, "idx": se.last["next_idx"],
                      "label": int(labels[se.last["next_idx"]]),
                      "prob": se.last["next_prob"]}
            for se in sessions
        }
        res = bucket.dispatch(reqs)
        assert len(res) == cap
        for se in sessions:
            se.last = res[se.slot]
            served[se.sid].append(res[se.slot])

    # sequential reference path, session by session
    sel = make_coda(jnp.asarray(serve_task.preds),
                    CODAHyperparams(n_parallel=cap))
    for se in sessions:
        ref_rows, ref = _drive_reference(sel, se.seed, labels, rounds)
        # _drive_reference labels `rounds` times following the same
        # propose->label loop, so row k is the state after k labels
        got = served[se.sid]
        assert len(got) == len(ref_rows)
        for k, ((r_idx, r_prob, r_best), g) in enumerate(zip(ref_rows, got)):
            assert g["next_idx"] == r_idx, (se.seed, k)
            assert g["best"] == r_best, (se.seed, k)
            # bitwise, not allclose: same bits or bust
            assert (np.float32(g["next_prob"]).tobytes()
                    == np.float32(r_prob).tobytes()), (se.seed, k)
        # the slab's carried state matches the reference selector's state
        # bitwise leaf-for-leaf as well
        slab_state = bucket.slot_state(se.slot)
        for a, b in zip(ref.state, slab_state):
            if a is not None:
                assert (np.asarray(a).tobytes()
                        == np.asarray(b).tobytes()), se.seed


def test_serve_batch_step_parity_modelpicker(serve_task):
    """Same parity for a stochastic selector (ModelPicker: random
    tie-breaks, posterior updates) — the key-stream contract is
    method-agnostic."""
    import jax.numpy as jnp

    from coda_tpu.selectors import make_modelpicker
    from coda_tpu.serve import SelectorSpec, SessionStore

    store = SessionStore(capacity=4)
    store.register_task("t", serve_task.preds)
    spec = SelectorSpec.create("model_picker")
    sessions = [store.open("t", spec, seed=s) for s in (0, 3)]
    bucket = sessions[0].bucket
    labels = np.asarray(serve_task.labels)

    res = bucket.dispatch({se.slot: {"do_update": False}
                           for se in sessions})
    for se in sessions:
        se.last = res[se.slot]
    hist = {se.sid: [res[se.slot]] for se in sessions}
    for _ in range(3):
        reqs = {se.slot: {"do_update": True, "idx": se.last["next_idx"],
                          "label": int(labels[se.last["next_idx"]]),
                          "prob": se.last["next_prob"]}
                for se in sessions}
        res = bucket.dispatch(reqs)
        for se in sessions:
            se.last = res[se.slot]
            hist[se.sid].append(res[se.slot])

    sel = make_modelpicker(jnp.asarray(serve_task.preds))
    for se in sessions:
        ref_rows, _ = _drive_reference(sel, se.seed, labels, 3)
        for k, ((r_idx, r_prob, r_best), g) in enumerate(
                zip(ref_rows, hist[se.sid])):
            assert g["next_idx"] == r_idx, (se.seed, k)
            assert g["best"] == r_best, (se.seed, k)
            assert (np.float32(g["next_prob"]).tobytes()
                    == np.float32(r_prob).tobytes()), (se.seed, k)


def test_serve_vmap_matches_map(serve_task):
    """The two slab-step lowerings (vmap = parallel-hardware axis, map =
    bitwise-reference serialization) agree: identical selections and best
    answers, scores to float tolerance (batched contractions may
    reassociate accumulation — the reason 'map' is the CPU default)."""
    from coda_tpu.serve import SelectorSpec, SessionStore

    labels = np.asarray(serve_task.labels)
    results = {}
    for impl in ("map", "vmap"):
        store = SessionStore(capacity=4, step_impl=impl)
        store.register_task("t", serve_task.preds)
        spec = SelectorSpec.create("coda", n_parallel=4)
        sessions = [store.open("t", spec, seed=s) for s in range(3)]
        bucket = sessions[0].bucket
        rows = []
        res = bucket.dispatch({se.slot: {"do_update": False}
                               for se in sessions})
        for se in sessions:
            se.last = res[se.slot]
        rows.append([res[se.slot] for se in sessions])
        for _ in range(3):
            reqs = {se.slot: {"do_update": True,
                              "idx": se.last["next_idx"],
                              "label": int(labels[se.last["next_idx"]]),
                              "prob": se.last["next_prob"]}
                    for se in sessions}
            res = bucket.dispatch(reqs)
            for se in sessions:
                se.last = res[se.slot]
            rows.append([res[se.slot] for se in sessions])
        results[impl] = rows
    for row_m, row_v in zip(results["map"], results["vmap"]):
        for g_m, g_v in zip(row_m, row_v):
            assert g_m["next_idx"] == g_v["next_idx"]
            assert g_m["best"] == g_v["best"]
            np.testing.assert_allclose(g_m["next_prob"], g_v["next_prob"],
                                       rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# slot lifecycle + backpressure
# ---------------------------------------------------------------------------

def test_serve_slot_reuse_after_close(serve_task):
    from coda_tpu.serve import SelectorSpec, SessionStore, UnknownSession

    store = SessionStore(capacity=2)
    store.register_task("t", serve_task.preds)
    spec = SelectorSpec.create("iid")
    s1 = store.open("t", spec, seed=0)
    s2 = store.open("t", spec, seed=1)
    assert {s1.slot, s2.slot} == {0, 1}
    bucket = s1.bucket
    r1 = bucket.dispatch({s1.slot: {"do_update": False}})[s1.slot]

    store.close(s1.sid)
    with pytest.raises(UnknownSession):
        store.get(s1.sid)
    assert bucket.live == 1

    # the freed slot is reused and its state re-initialized: same seed ->
    # the fresh session proposes the same first item with the same bits
    s3 = store.open("t", spec, seed=0)
    assert s3.slot == s1.slot
    r3 = bucket.dispatch({s3.slot: {"do_update": False}})[s3.slot]
    assert r3 == r1
    # s2 was untouched throughout
    assert bucket.live == 2
    store.close(s2.sid)
    store.close(s3.sid)
    assert bucket.live == 0


def test_serve_backpressure_full_slab(serve_task):
    from coda_tpu.serve import SelectorSpec, SessionStore, SlabFull

    store = SessionStore(capacity=2)
    store.register_task("t", serve_task.preds)
    spec = SelectorSpec.create("iid")
    a = store.open("t", spec, seed=0)
    store.open("t", spec, seed=1)
    with pytest.raises(SlabFull):
        store.open("t", spec, seed=2)
    # closing returns capacity
    store.close(a.sid)
    store.open("t", spec, seed=3)


def test_serve_stale_tickets_never_dispatch(serve_task):
    """A ticket that timed out (or whose session closed while queued) is
    dropped at dispatch time, not fired against a slot that may have been
    freed and reassigned — firing it would advance another session's PRNG
    stream or double-apply a retried label."""
    from coda_tpu.serve import Batcher, SelectorSpec, ServeMetrics, SessionStore

    store = SessionStore(capacity=2)
    store.register_task("t", serve_task.preds)
    spec = SelectorSpec.create("iid")
    batcher = Batcher(store, ServeMetrics(), max_wait=0.001).start()
    try:
        s1 = store.open("t", spec, seed=0)
        batcher.pause()
        # timed-out ticket: wait() cancels it before raising
        t_timeout = batcher.submit_start(s1)
        with pytest.raises(TimeoutError):
            t_timeout.wait(0.05)
        assert t_timeout.cancelled
        # closed-session ticket: queued, then the session goes away and
        # the slot is reassigned to a fresh session
        s2 = store.open("t", spec, seed=1)
        t_closed = batcher.submit_start(s2)
        store.close(s2.sid)
        s3 = store.open("t", spec, seed=2)
        assert s3.slot == s2.slot  # the slot was reused
        t_live = batcher.submit_start(s3)
        batcher.resume()
        with pytest.raises(RuntimeError, match="cancelled"):
            t_closed.wait(10.0)
        assert t_live.wait(10.0)["next_idx"] >= 0  # live traffic unaffected
        with pytest.raises(RuntimeError, match="cancelled"):
            t_timeout.wait(10.0)
    finally:
        batcher.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------

def _req(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(method, path,
                 body=None if body is None else json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, (json.loads(data) if data else {})


@pytest.fixture()
def serve_server(serve_task):
    from coda_tpu.serve import ServeApp, SelectorSpec, make_server

    app = ServeApp(capacity=3, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=3))
    app.add_task("tiny", serve_task.preds)
    app.start()
    srv = make_server(app, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1], app
    srv.shutdown()
    srv.server_close()
    app.drain(timeout=5.0)


def test_serve_http_end_to_end(serve_server):
    port, app = serve_server
    labels = None  # the server never sees oracle labels; we answer idx % C

    status, out = _req(port, "POST", "/session", {"seed": 0})
    assert status == 200
    sid = out["session"]
    assert out["task"] == "tiny"
    assert isinstance(out["idx"], int) and isinstance(out["best"], int)

    # label the proposed item; response advances to the next proposal
    first_idx = out["idx"]
    status, out = _req(port, "POST", f"/session/{sid}/label",
                       {"label": first_idx % 4, "idx": first_idx})
    assert status == 200
    assert out["n_labeled"] == 1

    # stale idx -> 409 (the client labeled an outdated proposal)
    status, err = _req(port, "POST", f"/session/{sid}/label",
                       {"label": 0, "idx": first_idx + 999})
    assert status == 409

    # out-of-range label -> 400; missing label -> 400
    status, _ = _req(port, "POST", f"/session/{sid}/label", {"label": 99})
    assert status == 400
    status, _ = _req(port, "POST", f"/session/{sid}/label", {})
    assert status == 400

    # GET best: cached answer + CODA's posterior read
    status, out = _req(port, "GET", f"/session/{sid}/best")
    assert status == 200
    assert isinstance(out["best"], int)
    assert len(out["pbest"]) == 5
    np.testing.assert_allclose(sum(out["pbest"]), 1.0, atol=1e-5)

    # stats reflect the traffic; open sessions and slab occupancy are
    # DISTINCT fields (they diverge the moment a session lives off-slab)
    status, stats = _req(port, "GET", "/stats")
    assert status == 200
    assert stats["open_sessions"] == 1
    assert stats["slab_occupancy"] == 1
    assert stats["tiers"] == {"hot": 1, "warm": 0, "cold": 0}
    assert stats["dispatches"] >= 2
    assert stats["requests"] >= 2
    assert stats["buckets"][0]["shape"] == [5, 48, 4]

    # unknown session -> 404, counted as a request refusal; close frees
    status, _ = _req(port, "POST", "/session/deadbeef/label", {"label": 0})
    assert status == 404
    status, stats = _req(port, "GET", "/stats")
    assert stats["requests_rejected"] >= 1
    status, _ = _req(port, "DELETE", f"/session/{sid}")
    assert status == 200
    status, stats = _req(port, "GET", "/stats")
    assert stats["open_sessions"] == 0
    assert stats["slab_occupancy"] == 0


def test_serve_http_admission_and_draining(serve_server):
    port, app = serve_server
    sids = []
    for s in range(3):
        status, out = _req(port, "POST", "/session", {"seed": s})
        assert status == 200
        sids.append(out["session"])
    # admission past slab capacity DEMOTES the coldest session instead of
    # answering 503 (the tiering contract: a wakeable session never turns
    # into backpressure) — open sessions exceed slab occupancy
    status, out = _req(port, "POST", "/session", {})
    assert status == 200
    sids.append(out["session"])
    _, stats = _req(port, "GET", "/stats")
    assert stats["open_sessions"] == 4
    assert stats["slab_occupancy"] == 3
    assert stats["demotions"] >= 1
    assert stats["sessions_rejected"] == 0
    # the demoted session still answers: the read transparently wakes it
    # (which pages out another LRU session to make room)
    status, out = _req(port, "GET", f"/session/{sids[0]}/best")
    assert status == 200
    _, stats = _req(port, "GET", "/stats")
    assert stats["wakes"] >= 1
    assert stats["open_sessions"] == 4

    # draining: no new sessions, existing ones still answered
    app.draining = True
    status, err = _req(port, "POST", "/session", {})
    assert status == 503
    assert "draining" in err["error"]
    status, h = _req(port, "GET", "/healthz")
    assert status == 200 and h["draining"] is True
    status, out = _req(port, "GET", f"/session/{sids[1]}/best")
    assert status == 200
    app.draining = False
    for sid in sids:
        _req(port, "DELETE", f"/session/{sid}")
    _, stats = _req(port, "GET", "/stats")
    assert stats["open_sessions"] == 0


# ---------------------------------------------------------------------------
# padded shape buckets
# ---------------------------------------------------------------------------

def test_serve_padded_bucket_never_proposes_phantoms():
    """bucket_n rounds N up; the zero-padded phantom items are deactivated
    through the shared ``unlabeled`` mask and must never be selected, all
    the way to pool exhaustion."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.serve import SelectorSpec, SessionStore

    task = make_synthetic_task(seed=3, H=4, N=20, C=3)
    store = SessionStore(capacity=2, bucket_n=32)
    store.register_task("small", task.preds)
    sess = store.open("small", SelectorSpec.create("iid"), seed=0)
    bucket = sess.bucket
    assert bucket.shape == (4, 32, 3)   # padded
    assert bucket.n_valid == 20

    seen = []
    res = bucket.dispatch({sess.slot: {"do_update": False}})[sess.slot]
    seen.append(res["next_idx"])
    for _ in range(19):  # label every real item
        res = bucket.dispatch({sess.slot: {
            "do_update": True, "idx": res["next_idx"],
            "label": res["next_idx"] % 3,
            "prob": res["next_prob"]}})[sess.slot]
        seen.append(res["next_idx"])
    assert all(0 <= i < 20 for i in seen[:-1])
    # 19 labels leave exactly one real unlabeled item; still no phantom
    assert 0 <= seen[-1] < 20


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_serve_metrics_snapshot_and_store(tmp_path):
    from coda_tpu.serve import ServeMetrics
    from coda_tpu.tracking import TrackingStore

    m = ServeMetrics()
    for i in range(100):
        m.record_dispatch(n_requests=16, queue_depth=i % 4,
                          seconds=0.001 * (1 + i % 10))
        m.record_request_latency(0.002 * (1 + i % 10))
    m.record_session("open")
    m.record_session("reject")

    snap = m.snapshot()
    assert snap["dispatches"] == 100
    assert snap["requests"] == 1600
    assert snap["max_occupancy"] == 16
    assert snap["sessions_opened"] == 1
    assert snap["sessions_rejected"] == 1
    assert snap["dispatch_latency"]["p50_ms"] == pytest.approx(6.0, rel=0.2)
    assert snap["dispatch_latency"]["p99_ms"] <= 10.0 + 1e-6
    assert snap["request_latency"]["p50_ms"] == pytest.approx(12.0, rel=0.2)

    db = str(tmp_path / "serve.sqlite")
    store = TrackingStore(db)
    m.log_to_store(store, experiment="serve-test",
                   params={"capacity": 16})
    rows = store.query(
        """SELECT m.key, m.value FROM metrics m
           JOIN runs r ON r.run_uuid = m.run_uuid
           JOIN experiments e ON e.experiment_id = r.experiment_id
           WHERE e.name = 'serve-test'""")
    got = dict(rows)
    assert got["dispatches"] == 100.0
    assert got["max_occupancy"] == 16.0
    assert "dispatch_latency.p50_ms" in got
    store.close()


# ---------------------------------------------------------------------------
# smoke-scale loadgen: the serving path end to end, every PR
# ---------------------------------------------------------------------------

def test_serve_loadgen_smoke(tmp_path, monkeypatch):
    """Tiny-shape lockstep loadgen on CPU: >= 16 sessions ride one
    dispatch, and the BENCH_SERVE json artifact has the required fields
    (sessions/sec, occupancy, p50/p99 latency)."""
    import scripts.serve_loadgen as lg

    monkeypatch.chdir(tmp_path)
    args = lg.parse_args([
        "--synthetic", "4,48,4", "--method", "coda",
        "--workers", "16", "--labels", "2", "--lockstep",
        "--capacity", "16", "--max-wait-ms", "1",
        "--out", str(tmp_path / "BENCH_SERVE_smoke.json"),
    ])
    report = lg.run_loadgen(args)

    assert report["n_errors"] == 0, report["errors"]
    assert report["server"]["max_occupancy"] >= 16
    assert report["sessions"] == 16
    assert report["sessions_per_s"] > 0
    assert report["latency_ms"]["p50"] is not None
    assert report["latency_ms"]["p99"] is not None
    assert report["server"]["dispatches"] >= 1

    # the script's writer path produces the artifact
    out = tmp_path / "BENCH_SERVE_smoke.json"
    with open(out, "w") as f:
        json.dump(report, f)
    assert json.loads(out.read_text())["server"]["max_occupancy"] >= 16


# ---------------------------------------------------------------------------
# continuous batching: linger cap + cancellation race + staged admission
# ---------------------------------------------------------------------------

def test_serve_linger_cap_bounds_formation(serve_task):
    """Steady trickle arrival (every gap < max_wait refreshes the window)
    must NOT stretch a tick's formation indefinitely: the total-linger cap
    dispatches the batch max_linger after its first ticket, bounded by
    time, not only by max_batch (the regression this PR's satellite
    pins)."""
    import time

    from coda_tpu.serve import Batcher, SelectorSpec, ServeMetrics, SessionStore

    store = SessionStore(capacity=8)
    store.register_task("t", serve_task.preds)
    spec = SelectorSpec.create("iid")
    sessions = [store.open("t", spec, seed=s) for s in range(8)]
    # warm the bucket's step outside the measurement (compile time would
    # otherwise swamp the formation-window assertion)
    sessions[0].bucket.warm()
    metrics = ServeMetrics()
    batcher = Batcher(store, metrics, max_batch=256,
                      max_wait=0.05, max_linger=0.15).start()
    try:
        t0 = time.perf_counter()
        first = batcher.submit_start(sessions[0])
        # trickle: a new ticket every ~25 ms (< max_wait, so the adaptive
        # gap window would refresh forever without the cap)
        feeder_done = threading.Event()

        def feeder():
            for s in sessions[1:]:
                time.sleep(0.025)
                batcher.submit_start(s)
            feeder_done.set()

        threading.Thread(target=feeder, daemon=True).start()
        first.wait(10.0)
        waited = time.perf_counter() - t0
        feeder_done.wait(5.0)
    finally:
        batcher.stop(drain=True, timeout=10.0)
    # the first ticket's tick must have formed within the cap (plus the
    # step itself and scheduling slack) — NOT after all 8 trickled in
    # (8 x 25 ms = 200 ms > max_linger alone, before the step)
    assert waited < 0.15 + 1.0, waited
    snap = metrics.snapshot()
    assert snap["dispatches"] >= 2  # the trickle spilled into later ticks
    assert snap["requests"] == 8    # everyone was served eventually


def test_serve_ticket_resolution_exactly_once(serve_task):
    """The cancel/complete race is arbitrated: whichever resolves first
    wins, the loser is a no-op — a ticket cancelled between collect and
    dispatch is never double-completed, and a result that lands before
    the cancel is kept (wait() returns it instead of raising)."""
    from coda_tpu.serve import Ticket

    # complete then cancel: result preserved, cancel loses
    t = Ticket(session=None, do_update=False)
    assert t.complete({"next_idx": 1}) is True
    assert t.cancel() is False
    assert not t.cancelled
    assert t.wait(0.1) == {"next_idx": 1}  # lost-race wait gets the result

    # cancel then complete: cancel wins, completion is a no-op
    t = Ticket(session=None, do_update=False)
    assert t.cancel() is True
    assert t.complete({"next_idx": 2}) is False
    assert t.result is None
    with pytest.raises(RuntimeError, match="cancelled"):
        t.wait(0.1)

    # fail after cancel: also a no-op (the dispatcher's drop path racing
    # a wait()-timeout must not overwrite the first resolution)
    t = Ticket(session=None, do_update=False)
    assert t.cancel() is True
    assert t.fail(ValueError("boom")) is False
    with pytest.raises(RuntimeError, match="cancelled"):
        t.wait(0.1)


def test_serve_cancel_between_collect_and_dispatch(serve_task):
    """A ticket cancelled after submission but before its tick dispatches
    is dropped with its slot clean: the next tick serves the same slot's
    session normally and the cancelled ticket is resolved exactly once."""
    from coda_tpu.serve import Batcher, SelectorSpec, ServeMetrics, SessionStore

    store = SessionStore(capacity=2)
    store.register_task("t", serve_task.preds)
    spec = SelectorSpec.create("iid")
    batcher = Batcher(store, ServeMetrics(), max_wait=0.001).start()
    try:
        s1 = store.open("t", spec, seed=0)
        batcher.pause()
        # queued but cancelled before the batcher can dispatch it
        t_cancelled = batcher.submit_start(s1)
        assert t_cancelled.cancel() is True
        t_live = batcher.submit_start(s1)  # same SLOT, next in queue
        batcher.resume()
        res = t_live.wait(30.0)
        assert res["next_idx"] >= 0  # the slot dispatched cleanly
        with pytest.raises(RuntimeError, match="cancelled"):
            t_cancelled.wait(1.0)
        assert t_cancelled.result is None  # never double-completed
        # the session advanced exactly once (one live ticket, one dropped)
        assert s1.last == res
    finally:
        batcher.stop(drain=False, timeout=10.0)


def test_serve_staged_admission_lifecycle(serve_task):
    """Admission stages its slab write (no dispatch lock); an open that is
    closed before any dispatch drops its staged write, and the slot's next
    tenant gets its own correct state — pinned against a fresh store."""
    from coda_tpu.serve import SelectorSpec, SessionStore

    spec = SelectorSpec.create("coda", n_parallel=2)

    store = SessionStore(capacity=2)
    store.register_task("t", serve_task.preds)
    a = store.open("t", spec, seed=7)
    store.close(a.sid)              # staged write dropped, slot freed
    b = store.open("t", spec, seed=11)
    assert b.slot == a.slot
    got = b.bucket.dispatch({b.slot: {"do_update": False}})[b.slot]

    ref_store = SessionStore(capacity=2)
    ref_store.register_task("t", serve_task.preds)
    r = ref_store.open("t", spec, seed=11)
    want = r.bucket.dispatch({r.slot: {"do_update": False}})[r.slot]
    assert got == want


# ---------------------------------------------------------------------------
# donated slab buffers: the bitwise pin (acceptance criterion)
# ---------------------------------------------------------------------------

def test_serve_donated_step_bitwise(serve_task):
    """Donated (in-place carries, AOT-warm) and undonated (copying,
    lazy-jit) slab-step paths produce bitwise-identical session
    trajectories AND slab states — donation changes buffer lifetime, never
    numerics."""
    from coda_tpu.serve import SelectorSpec, SessionStore

    labels = np.asarray(serve_task.labels)

    def run(donate: bool, warm: bool):
        store = SessionStore(capacity=4, donate=donate)
        store.register_task("t", serve_task.preds)
        spec = SelectorSpec.create("coda", n_parallel=4)
        if warm:
            store._bucket_for("t", spec).warm()
        sessions = [store.open("t", spec, seed=s) for s in range(3)]
        bucket = sessions[0].bucket
        rows = []
        res = bucket.dispatch({se.slot: {"do_update": False}
                               for se in sessions})
        for se in sessions:
            se.last = res[se.slot]
        rows.append([res[se.slot] for se in sessions])
        for _ in range(4):
            reqs = {se.slot: {"do_update": True,
                              "idx": se.last["next_idx"],
                              "label": int(labels[se.last["next_idx"]]),
                              "prob": se.last["next_prob"]}
                    for se in sessions}
            res = bucket.dispatch(reqs)
            for se in sessions:
                se.last = res[se.slot]
            rows.append([res[se.slot] for se in sessions])
        states = [bucket.slot_state(se.slot) for se in sessions]
        return rows, states

    rows_d, states_d = run(donate=True, warm=True)
    rows_u, states_u = run(donate=False, warm=False)
    # next_prob floats compare EXACTLY (dict equality on python floats
    # from float32 — same bits or bust), as do idx/best/stochastic
    assert rows_d == rows_u
    for sd, su in zip(states_d, states_u):
        for a, b in zip(sd, su):
            if a is not None:
                assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


# ---------------------------------------------------------------------------
# warm pool: readiness gate + restart with a persistent compilation cache
# ---------------------------------------------------------------------------

def test_serve_healthz_readiness_gate(serve_task):
    """/healthz answers 503 until the warm pool is compiled, 200 after —
    the load balancer's signal to keep traffic off a cold replica."""
    from coda_tpu.serve import ServeApp, SelectorSpec, make_server

    app = ServeApp(capacity=2, max_wait=0.001,
                   spec=SelectorSpec.create("iid"))
    app.add_task("tiny", serve_task.preds)
    app.batcher.start()             # serving thread up, pool NOT warm
    srv = make_server(app, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    port = srv.server_address[1]
    try:
        status, h = _req(port, "GET", "/healthz")
        assert status == 503
        assert h["ready"] is False and h["ok"] is False
        info = app.warm()
        assert info["size"] >= 2 and app.ready.is_set()
        status, h = _req(port, "GET", "/healthz")
        assert status == 200
        assert h["ready"] is True and h["ok"] is True
        # stats carries the warm-pool evidence
        status, stats = _req(port, "GET", "/stats")
        assert stats["ready"] is True
        assert stats["warm_pool"]["size"] == info["size"]
        assert stats["buckets"][0]["warm"] is True
    finally:
        srv.shutdown()
        srv.server_close()
        app.drain(timeout=5.0)


def test_serve_warm_pool_restart_zero_fresh_compiles(tmp_path):
    """The acceptance criterion: a second server start against a populated
    --compilation-cache-dir performs 0 fresh backend compiles — every
    warm-pool executable deserializes (persistent-cache miss counter stays
    0 while the hit counter counts the pool)."""
    import os
    import subprocess
    import sys

    script = r"""
import json, sys
from coda_tpu.utils.platform import enable_compilation_cache
enable_compilation_cache(sys.argv[1])
from coda_tpu.data import make_synthetic_task
from coda_tpu.serve import ServeApp, SelectorSpec
from coda_tpu.telemetry import get_registry
task = make_synthetic_task(seed=0, H=3, N=16, C=3)
app = ServeApp(capacity=2, max_wait=0.001, spec=SelectorSpec.create("iid"))
app.add_task("t", task.preds)
app.start(warm=True)
out = app.open_session(seed=0)      # one warm dispatch over the pool
app.close_session(out["session"])
app.drain(timeout=10)
reg = get_registry()
print(json.dumps({
    "misses": reg.counter("persistent_cache_misses_total").value(),
    "hits": reg.counter("persistent_cache_hits_total").value(),
    "compile_events": reg.counter("jit_compiles_total").value(),
    "warm_size": app.warm_info.get("size"),
    "warm_misses": app.metrics.warm_misses,
    "ready": app.ready.is_set(),
}))
"""
    cache = str(tmp_path / "jaxcache")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def start_once():
        out = subprocess.run(
            [sys.executable, "-c", script, cache], env=env,
            capture_output=True, text=True, timeout=420,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = start_once()
    assert cold["ready"] and cold["warm_size"] >= 2
    assert cold["misses"] > 0          # cold start compiled for real
    assert cold["warm_misses"] == 0    # but never under traffic

    warm = start_once()
    assert warm["ready"] and warm["warm_size"] == cold["warm_size"]
    assert warm["misses"] == 0, (
        f"second start performed {warm['misses']} fresh backend compiles "
        "against a populated compilation cache")
    assert warm["hits"] > 0            # the pool deserialized
    assert warm["warm_misses"] == 0


# ---------------------------------------------------------------------------
# record/replay: continuous batching must not change any trajectory
# ---------------------------------------------------------------------------

def test_serve_recorder_stream_invariant_to_tick_grouping(serve_task):
    """The same sessions driven through COALESCED ticks (lockstep: all
    sessions ride one dispatch per round) and through CONTINUOUS one-at-a-
    time dispatches produce bitwise-identical per-session recorder streams
    — tick grouping is a scheduling detail, never a numerics input (the
    record/replay compatibility pin)."""
    from coda_tpu.serve import ServeApp, SelectorSpec

    labels = np.asarray(serve_task.labels)
    n_sessions, rounds = 3, 4

    def drive(coalesced: bool):
        app = ServeApp(capacity=n_sessions, max_wait=0.001,
                       spec=SelectorSpec.create("model_picker"))
        app.add_task("t", serve_task.preds)
        app.start(warm=True)
        sids = [app.open_session(seed=s)["session"]
                for s in range(n_sessions)]
        for _ in range(rounds):
            if coalesced:
                # all sessions' labels ride ONE dispatch (lockstep hook)
                app.batcher.pause()
                tickets = []
                for sid in sids:
                    sess = app.store.get(sid)
                    cur = sess.last
                    tickets.append(app.batcher.submit_label(
                        sess, idx=cur["next_idx"],
                        label=int(labels[cur["next_idx"]]),
                        prob=cur["next_prob"]))
                app.batcher.resume()
                for t in tickets:
                    t.wait(30.0)
            else:
                # one dispatch per request: maximally different grouping
                for sid in sids:
                    sess = app.store.get(sid)
                    cur = sess.last
                    app.label(sid, int(labels[cur["next_idx"]]),
                              idx=cur["next_idx"])
        streams = {sid: app.recorder.history(sid) for sid in sids}
        app.drain(timeout=10.0)
        return streams

    coalesced = drive(True)
    continuous = drive(False)
    for sid_c, sid_s in zip(coalesced, continuous):
        rows_c, rows_s = coalesced[sid_c], continuous[sid_s]
        assert len(rows_c) == len(rows_s) == rounds + 1
        for rc, rs in zip(rows_c, rows_s):
            assert rc == rs  # dict equality: floats must match exactly


# ---------------------------------------------------------------------------
# loadgen mux mode + the committed-bench gate (tier-1 wiring)
# ---------------------------------------------------------------------------

def test_serve_loadgen_mux_smoke(tmp_path):
    """Asyncio mux arrival end to end (the >=256-session driver, at smoke
    scale): 0 errors, warm pool hit on every dispatch, and the queue-wait /
    dispatch / step breakdown present for mechanical p99 attribution."""
    import scripts.serve_loadgen as lg

    args = lg.parse_args([
        "--synthetic", "4,48,4", "--method", "coda",
        "--mux", "--workers", "12", "--sessions", "18", "--labels", "2",
        "--capacity", "12", "--max-wait-ms", "5", "--max-linger-ms", "40",
    ])
    report = lg.run_loadgen(args)
    assert report["n_errors"] == 0, report["errors"]
    assert report["mode"] == "mux"
    assert report["sessions"] == 18
    assert report["warm_pool"]["size"] >= 3
    assert report["warm_pool"]["misses"] == 0
    assert report["latency_ms"]["p99"] is not None
    for phase in ("queue_wait", "dispatch", "step"):
        assert report["breakdown"][phase]["p99_ms"] is not None, phase
    assert report["breakdown"]["spans"]["n_tick_spans"] >= 1


def _load_check_perf():
    import importlib.util
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_perf_serve_test",
        os.path.join(repo, "scripts", "check_perf.py"))
    mod = importlib.util.module_from_spec(spec)
    # registered BEFORE exec: the module defines dataclasses, whose field
    # resolution looks itself up through sys.modules
    sys.modules["check_perf_serve_test"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_check_perf_serve_contract_gates_committed_artifact():
    """Tier-1 wiring of the serve contract in the check_perf.py registry
    (``check_serve_bench.py``'s shim was folded into ``--family serve``):
    the committed BENCH_SERVE_CPU_r09.json satisfies the schema and the
    committed latency bounds (>= 256 sessions, 0 errors, p99 within the
    10x-vs-r06 contract), and a regressed/degraded report is rejected."""
    import copy
    import os

    mod = _load_check_perf()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "BENCH_SERVE_CPU_r09.json")
    with open(path) as f:
        report = json.load(f)
    assert mod.serve_check_report(report) == []

    bad = copy.deepcopy(report)
    bad["latency_ms"]["p99"] = mod.P99_MS_MAX + 1
    assert any("p99" in v for v in mod.serve_check_report(bad))
    bad = copy.deepcopy(report)
    bad["n_errors"] = 3
    assert any("n_errors" in v for v in mod.serve_check_report(bad))
    bad = copy.deepcopy(report)
    del bad["breakdown"]
    assert any("breakdown" in v for v in mod.serve_check_report(bad))
    bad = copy.deepcopy(report)
    bad["warm_pool"]["misses"] = 2
    assert any("misses" in v for v in mod.serve_check_report(bad))
    # the folded CLI: the old check_serve_bench default invocation is now
    # `check_perf.py --family serve` (no args = the committed serve set)
    assert mod.main(["--family", "serve"]) == 0
    assert mod.main(["--family", "serve", path]) == 0


def test_serve_pause_holds_even_full_batches(serve_task):
    """pause() freezes ticking even when a full max_batch is already
    queued: nothing dispatches until resume (the lockstep determinism
    contract), and everything queued is then served."""
    import time

    from coda_tpu.serve import Batcher, SelectorSpec, ServeMetrics, SessionStore

    store = SessionStore(capacity=6)
    store.register_task("t", serve_task.preds)
    spec = SelectorSpec.create("iid")
    sessions = [store.open("t", spec, seed=s) for s in range(6)]
    sessions[0].bucket.warm()
    metrics = ServeMetrics()
    batcher = Batcher(store, metrics, max_batch=4, max_wait=0.001).start()
    try:
        batcher.pause()
        tickets = [batcher.submit_start(s) for s in sessions]  # 6 > max_batch
        time.sleep(0.3)
        assert metrics.snapshot()["dispatches"] == 0  # frozen while paused
        batcher.resume()
        for t in tickets:
            assert t.wait(30.0)["next_idx"] >= 0
        snap = metrics.snapshot()
        assert snap["requests"] == 6
        assert snap["dispatches"] == 2  # max_batch split: 4 + 2
    finally:
        batcher.stop(drain=False, timeout=10.0)


def test_warm_pool_cost_attribution_on_stats_and_metrics(serve_task):
    """The performance-observatory acceptance surface: after warm-up,
    /stats exposes per-bucket executable FLOPs / bytes / peak-HBM and a
    roofline class for every warm-pool program, and /metrics carries the
    executable_* gauge families — present-and-finite on CPU, no
    hard-coded backend numbers."""
    import math

    from coda_tpu.serve import SelectorSpec, ServeApp
    from coda_tpu.telemetry import lint_prometheus, render_prometheus

    app = ServeApp(capacity=2, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=2))
    app.add_task("tiny", serve_task.preds)
    app.start()
    try:
        stats = app.stats()
        (bucket,) = stats["buckets"]
        cost = bucket["cost"]
        # every warm-pool program is attributed (coda has a pbest read;
        # donation gives the slot writer)
        assert {"step", "init", "pbest", "write_slot"} <= set(cost)
        for program, entry in cost.items():
            assert entry["flops"] > 0 and math.isfinite(entry["flops"]), \
                program
            assert entry["bytes_accessed"] > 0
            assert entry["peak_hbm_bytes"] > 0
            assert entry["roofline_class"] in ("compute-bound",
                                               "memory-bound")
            assert math.isfinite(entry["arithmetic_intensity"])
            assert math.isfinite(entry["machine_balance"])
        # the slab step dominates the tick: its working set and traffic
        # must dwarf the one-slot programs' (the machine-read version of
        # "99% of tick wall is one slab step")
        assert cost["step"]["bytes_accessed"] > \
            cost["pbest"]["bytes_accessed"]
        text = render_prometheus(app.telemetry.registry,
                                 serve_metrics=app.metrics)
        assert 'coda_executable_flops{' in text
        assert 'coda_executable_roofline{' in text
        assert f'name="serve/tiny/coda/' in text
        assert lint_prometheus(text) == []
    finally:
        app.drain(timeout=5.0)
