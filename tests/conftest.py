"""Test env: CPU backend with 8 virtual devices so multi-chip sharding tests
run without TPUs (same trick the driver's dryrun uses). Must run before any
jax import."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The environment's site hook may force-register a TPU platform and override
# JAX_PLATFORMS; pinning the config (before any backend is initialized) keeps
# tests on the virtual-device CPU backend regardless.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: test wall-clock is dominated by XLA compiles of
# shape-stable programs (every test re-jits the same tiny-shape experiment
# programs), and the cache works on the CPU backend — a warm rerun of the
# full suite skips nearly all of that. Override the location with
# CODA_TEST_COMPILE_CACHE=; disable with CODA_TEST_COMPILE_CACHE=off.
_cache = os.environ.get(
    "CODA_TEST_COMPILE_CACHE",
    os.path.join(os.path.dirname(__file__), "..", ".jax_test_cache"),
)
if _cache != "off":
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np
import pytest

# Tests measured >~7 s on a warm compile cache (full-shape lockstep parity,
# multi-round resumable scans, subprocess-spawning distributed/demo/paper
# flows). The FAST signal is `pytest -m "not slow" -q` (<90 s target;
# add `-n 4` to parallelize); the FULL suite stays the default run and
# includes everything. Parametrized tests match on the base name.
_SLOW_TESTS = {
    "test_large_c_sharded_execution_parity",
    "test_eig_precision_plumbing",
    "test_resumable_matches_single_scan",
    "test_pi_delta_matches_exact_recompute",
    "test_two_process_sharded_experiment_trace_parity",
    "test_coda_rowscan_matches_factored",
    "test_coda_real_digits_independent_trace_parity",
    "test_coda_real_widepool_independent_trace_parity",
    "test_cli_debug_viz_and_profile",
    "test_resumable_bf16_cache_roundtrips",
    "test_coda_incremental_cache_row_refresh_exact",
    "test_coda_incremental_matches_factored_trace",
    "test_imagenet_scale_aot_memory_analysis",
    "test_sharded_trace_matches_single_device",
    "test_sharded_pallas_trace_matches_single_device",
    "test_modelpicker_static_trim_matches_full_scoring",
    "test_run_seeds_compiled_matches_run_seeds",
    "test_coda_real_binary_independent_trace_parity",
    "test_coda_real_text_independent_trace_parity",
    "test_coda_prefilter_fallback_scores_all_unlabeled",
    "test_sharded_eig_scores_match",
    "test_eig_chunk_invariance_finite_nonneg",
    "test_suite_batched_matches_unbatched",
    "test_suite_batched_caps_split_dispatches",
    "test_hf_pipeline_scorer_real_checkpoint",
    "test_coda_converges_and_beats_iid",
    "test_fingerprint_mismatch_raises",
    "test_resume_after_interrupt",
    "test_resume_with_smaller_iters",
    "test_suite_modelpicker_per_task_epsilon",
    "test_coda_auto_mode_resolution",
    "test_suite_runs_and_reuses_compiles",
    "test_pallas_kernels_vmap_fallback",
    "test_demo_full_loop",
    "test_paper_scripts_end_to_end",
    "test_gather_matches_xla_path",
    "test_fused_compute_refresh_real_data_trace",
    "test_fused_compute_long_horizon_widepool_trace",
    "test_recorder_overhead_under_five_percent",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.name.split("[")[0] in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def tiny_task():
    from coda_tpu.data import make_synthetic_task

    return make_synthetic_task(seed=0, H=5, N=48, C=4)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)
