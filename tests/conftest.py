"""Test env: CPU backend with 8 virtual devices so multi-chip sharding tests
run without TPUs (same trick the driver's dryrun uses). Must run before any
jax import."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The environment's site hook may force-register a TPU platform and override
# JAX_PLATFORMS; pinning the config (before any backend is initialized) keeps
# tests on the virtual-device CPU backend regardless.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: test wall-clock is dominated by XLA compiles of
# shape-stable programs (every test re-jits the same tiny-shape experiment
# programs), and the cache works on the CPU backend — a warm rerun of the
# full suite skips nearly all of that. Override the location with
# CODA_TEST_COMPILE_CACHE=; disable with CODA_TEST_COMPILE_CACHE=off.
_cache = os.environ.get(
    "CODA_TEST_COMPILE_CACHE",
    os.path.join(os.path.dirname(__file__), "..", ".jax_test_cache"),
)
if _cache != "off":
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def tiny_task():
    from coda_tpu.data import make_synthetic_task

    return make_synthetic_task(seed=0, H=5, N=48, C=4)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)
