"""Unit tests for bench.py's measurement protocol helpers.

The benchmark's credibility rests on these pieces (VERDICT r2: the committed
numbers were measurement artifacts), so they get direct tests: robust noise
estimation, the analytic FLOP model staying in lockstep with the kernel
resolver, and the cached multi-size reference-baseline bookkeeping.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench


def test_mad_robust_to_single_outlier():
    # one tunnel hiccup (observed: a rep taking 6x the median) must not
    # inflate the noise floor the linearity guard compares against
    walls = [1.81, 1.82, 1.815, 1.87, 11.4]
    assert bench._mad(walls) < 0.06
    assert np.std(walls) > 3.0  # the non-robust estimate the guard replaced


def test_analytic_flops_follows_resolver():
    from coda_tpu.selectors import CODAHyperparams
    from coda_tpu.selectors.coda import resolve_eig_mode

    # headline config resolves incremental; flops must be the row-refresh
    # model, ~C-fold below the factored count
    f_inc, m_inc, pi_inc = bench._analytic_step_flops(1000, 50_000, 10)
    assert m_inc == resolve_eig_mode(CODAHyperparams(), 1000, 50_000, 10)
    assert m_inc == "incremental"
    f_fac, m_fac, _ = bench._analytic_step_flops(1000, 50_000, 10,
                                                 mode="factored")
    assert m_fac == "factored"
    assert f_fac / f_inc > 5  # C=10 cuts the dominant einsums ~10x

    # past the cache budget auto must fall back -> factored FLOPs
    f_big, m_big, _ = bench._analytic_step_flops(1000, 200_000, 10)
    assert m_big == "factored"
    assert f_big > f_fac

    # pin both models to the documented kernel shapes: incremental pays
    # the resolved pi-hat refresh (delta gather on CPU, the exact column
    # einsum on TPU), factored the full C^2 pass (update_pi_hat)
    H, N, C, G = 1000, 50_000, 10, 256
    pi_flops = 2.0 * H * N if pi_inc == "delta" else 2.0 * H * N * C
    assert f_inc == 6.0 * N * H * G + pi_flops + 10.0 * N * C * H
    assert f_fac == 6.0 * N * C * H * G + 2.0 * H * C * C * N

    # the pi_update resolution follows the explicit override
    f_d, _, pi_d = bench._analytic_step_flops(1000, 50_000, 10,
                                              pi_update="delta")
    f_e, _, pi_e = bench._analytic_step_flops(1000, 50_000, 10,
                                              pi_update="exact")
    assert (pi_d, pi_e) == ("delta", "exact")
    assert f_e - f_d == 2.0 * H * N * C - 2.0 * H * N
    # and the byte model prices exact as the full-tensor stream
    b_d = bench._analytic_step_bytes(H, N, C, "incremental", pi_update="delta")
    b_e = bench._analytic_step_bytes(H, N, C, "incremental", pi_update="exact")
    assert b_e - b_d == 4.0 * H * N * C - 4.0 * H * N


def test_reference_baseline_cache_roundtrip(tmp_path, monkeypatch):
    # pre-seed the cache with all three sizes: no measurement should run
    cache = {"sizes": {}}
    for h, n in bench.REF_SIZES:
        cache["sizes"][f"h{h}_n{n}_c10"] = {
            "steps_per_sec": 1000.0 / (h * n), "steps": 5,
            "H": h, "N": n, "C": 10,
        }
    path = tmp_path / "bench_baseline.json"
    path.write_text(json.dumps(cache))
    monkeypatch.setattr(bench, "BASELINE_CACHE", str(path))

    def boom(*a, **k):  # measurement must not be invoked on a warm cache
        raise AssertionError("measure_reference_at called despite cache")

    monkeypatch.setattr(bench, "measure_reference_at", boom)
    base = bench.reference_baseline(10, skip=False)
    # k = sps * H * N was seeded constant => perfect linearity
    assert base["linearity_dev"] == pytest.approx(0.0, abs=1e-12)
    assert base["k_mean"] == pytest.approx(1000.0)
    assert len(base["sizes"]) == 3


def test_reference_baseline_skip_without_cache(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "BASELINE_CACHE", str(tmp_path / "nope.json"))
    assert bench.reference_baseline(10, skip=True) == {}


def test_analytic_step_bytes_matches_documented_traffic():
    """The bytes model feeds the reported MBU; pin it to the documented
    per-round traffic per tier: incremental = cache stream + dense
    posterior Beta reduction + delta pi-hat gather + row write+read (the
    pi_update='delta' path), factored = hyp stream + full preds stream +
    row. A sparse:K posterior replaces the dense (H, C, C) reduction with
    the compact row read."""
    from bench import _analytic_step_bytes

    H, N, C = 1000, 50_000, 10
    post = 4.0 * H * C * C
    expected = 4.0 * N * C * H + post + 4.0 * H * N + 8.0 * N * H
    assert _analytic_step_bytes(
        H, N, C, mode="incremental", pi_update="delta") == expected
    # sparse:K swaps the dense posterior stream for the O(H*K) row slices
    k = 4
    assert _analytic_step_bytes(
        H, N, C, mode="incremental", pi_update="delta",
        posterior=f"sparse:{k}") == expected - post + 16.0 * H * k
    expected_fac = 4.0 * N * C * H + 4.0 * H * N * C + 8.0 * N * H
    assert _analytic_step_bytes(
        H, N, C, mode="factored", pi_update="delta") == expected_fac
    # arithmetic intensity stays far below a v5e's ~240 FLOP/byte balance:
    # the kernel is bandwidth-bound and MBU is the honest roofline
    from bench import _analytic_step_flops

    flops, mode, pi_res = _analytic_step_flops(H, N, C)
    assert mode == "incremental"
    assert flops / _analytic_step_bytes(
        H, N, C, mode=mode, pi_update=pi_res) < 60


def test_mbu_reported_against_known_chip():
    """bench_ours wires bytes/s through to mbu only when the chip's peak
    bandwidth is known and the linearity guard passed."""
    from bench import _PEAK_HBM_BPS

    # every chip with a FLOP peak also has a bandwidth peak (the two
    # tables must stay in lockstep or mbu silently reports None)
    from bench import _PEAK_FLOPS

    assert set(_PEAK_HBM_BPS) == set(_PEAK_FLOPS)


def test_analytic_bytes_prices_fused_pallas_backend():
    """The fused refresh+score kernel reads the donated cache once and
    writes back ONLY the refreshed class row (row-only aliased write); the
    byte model must charge the row roundtrip through the kernel but NOT a
    full-cache rewrite."""
    from bench import _analytic_step_bytes

    H, N, C = 1000, 50_000, 10
    jnp_b = _analytic_step_bytes(H, N, C, "incremental", pi_update="exact")
    pal_b = _analytic_step_bytes(H, N, C, "incremental", pi_update="exact",
                                 backend="pallas")
    cache = 4.0 * N * C * H + 4.0 * H * C * C  # + dense posterior stream
    assert pal_b == cache + 4.0 * H * N * C + 16.0 * N * H
    # vs the jnp path: the kernel adds the (N, H) fp32 row roundtrip but
    # saves the defensive copy XLA inserts around the DUS (not priced —
    # the model charges pure algorithmic traffic for both backends)
    assert pal_b == jnp_b + 8.0 * N * H


def test_bench_cost_section_present_and_finite():
    """The machine-read cost section of the bench output (acceptance:
    fields present-and-finite on CPU, no hard-coded backend numbers): XLA
    whole-program analysis of the timed executable + the analytic
    per-step roofline classification against the shared peak table."""
    import math

    ours = bench.bench_ours(8, 64, 3, iters=2, eig_chunk=64, reps=2)
    cost = ours["cost"]
    for key in ("xla_flops", "xla_bytes_accessed", "peak_hbm_bytes",
                "arithmetic_intensity", "machine_balance"):
        assert isinstance(cost[key], (int, float)) and \
            math.isfinite(cost[key]), key
    assert cost["xla_flops"] > 0 and cost["xla_bytes_accessed"] > 0
    assert cost["roofline_class"] in ("compute-bound", "memory-bound")
    assert cost["flop_accounting"] == "analytic_per_step"
    # on an unknown device kind the classification uses the documented
    # default balance and SAYS so; on a known chip it cites the table
    assert cost["peak_source"] in ("table", "default_balance")
    if cost["peak_source"] == "default_balance":
        assert cost["peak_flops_per_sec"] is None
    else:
        assert cost["peak_flops_per_sec"] > 0
    # the harvest also landed in the process cost book (telemetry.json's
    # costs section)
    from coda_tpu.telemetry import COSTS

    assert any(k.startswith("bench/coda/8x64x3/")
               for k in COSTS.snapshot(site="bench"))


def test_bench_output_is_fingerprinted():
    """bench.py stamps the recorder's environment fingerprint so captures
    are attributable and cross-round comparable (check_perf keys
    same-fingerprint regression on it)."""
    from coda_tpu.telemetry.recorder import environment_fingerprint

    fp = environment_fingerprint(knobs={"eig_entropy": "approx"})
    assert fp["backend"] == "cpu"
    assert fp["knobs"]["eig_entropy"] == "approx"
    # the peak table bench reports MFU/MBU against is the ONE shared
    # table in telemetry/costs.py
    from coda_tpu.telemetry import costs

    assert bench._PEAK_FLOPS is costs.PEAK_FLOPS
    assert bench._PEAK_HBM_BPS is costs.PEAK_HBM_BPS
