"""Multi-process distributed backend smoke test.

Launches two real OS processes that join one ``jax.distributed`` runtime
through ``coda_tpu.parallel.distributed.initialize`` (CPU backend, one
virtual device each) and run a cross-process psum — catching coordinator
env-var/API drift that the in-process no-op path can't
(``parallel/distributed.py:28-55``; SURVEY.md §5 distributed comm backend).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")  # site hook may register axon
sys.path.insert(0, os.environ["CODA_REPO"])
from coda_tpu.parallel.distributed import initialize, is_primary

pid = int(sys.argv[1])
ok = initialize(coordinator_address=os.environ["COORD"],
                num_processes=2, process_id=pid)
assert ok, "initialize returned False in a 2-process config"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()
assert jax.local_device_count() == 1
assert is_primary() == (pid == 0)

import jax.numpy as jnp

# one local device per process; pmap's axis spans all GLOBAL devices, so the
# psum crosses the process boundary through the distributed runtime (gloo
# host collectives — selected by initialize(); the default CPU client
# refuses multiprocess computations outright)
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.asarray([float(pid + 1)])
)
# tolerance, not equality: a cross-process psum reassociates the fp32
# reduction, so partial-sum order may drift from the serial sum by ~1 ulp
assert abs(float(out[0]) - 3.0) <= 1e-6 * 3.0, float(out[0])
print(f"worker {pid} psum ok", flush=True)
"""


def _run_two_workers(worker_src: str, tmp_path, timeout: float = 300.0):
    """Launch two coordinator-joined worker processes and return their
    outputs, asserting both exited 0 and printed their marker line.

    The coordinator port is picked by bind-then-close — inherently TOCTOU
    (jax.distributed must bind the port itself), so a rare collision on a
    busy host surfaces as the communicate timeout; centralizing here keeps
    any future hardening in one place.
    """
    worker = tmp_path / "worker.py"
    worker.write_text(worker_src)
    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env["COORD"] = f"127.0.0.1:{port}"
    env["CODA_REPO"] = os.path.join(os.path.dirname(__file__), "..")
    env.pop("JAX_COORDINATOR", None)
    procs = [
        subprocess.Popen([sys.executable, str(worker), str(pid)], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
    return outs


def test_two_process_psum(tmp_path):
    outs = _run_two_workers(_WORKER, tmp_path, timeout=150)
    for pid, out in enumerate(outs):
        assert f"worker {pid} psum ok" in out


def test_single_process_is_noop(monkeypatch):
    from coda_tpu.parallel.distributed import initialize

    monkeypatch.delenv("JAX_COORDINATOR", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize() is False


_EXPERIMENT_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")  # site hook may register axon
sys.path.insert(0, os.environ["CODA_REPO"])
from coda_tpu.parallel.distributed import initialize

pid = int(sys.argv[1])
assert initialize(coordinator_address=os.environ["COORD"],
                  num_processes=2, process_id=pid)
assert jax.device_count() == 4 and jax.local_device_count() == 2

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from coda_tpu.data import make_synthetic_task
from coda_tpu.engine.loop import make_batched_experiment_fn
from coda_tpu.parallel import DATA_AXIS, make_mesh
from coda_tpu.selectors import CODAHyperparams, make_coda

task = make_synthetic_task(seed=0, H=4, N=32, C=3)  # same tensor on both procs
preds_np, labels_np = np.asarray(task.preds), np.asarray(task.labels)
mesh = make_mesh(data=4)  # spans BOTH processes' devices
psh = NamedSharding(mesh, P(None, DATA_AXIS, None))
preds = jax.make_array_from_callback(preds_np.shape, psh,
                                     lambda idx: preds_np[idx])
labels = jax.make_array_from_callback(
    labels_np.shape, NamedSharding(mesh, P(DATA_AXIS)),
    lambda idx: labels_np[idx])

iters = 6
hp = CODAHyperparams(eig_chunk=32, num_points=64)
fn = make_batched_experiment_fn(lambda p: make_coda(p, hp), iters=iters)
keys = jnp.stack([jax.random.PRNGKey(0)])
res = jax.jit(fn)(preds, labels, keys)
# per-round traces are replicated scalars -> readable on every process
assert res.chosen_idx.is_fully_replicated
got_idx = np.asarray(res.chosen_idx)[0]
got_best = np.asarray(res.best_model)[0]

# in-process single-device reference of the same program
ref = jax.jit(fn)(jnp.asarray(preds_np), jnp.asarray(labels_np), keys)
np.testing.assert_array_equal(got_idx, np.asarray(ref.chosen_idx)[0])
np.testing.assert_array_equal(got_best, np.asarray(ref.best_model)[0])
print(f"worker {pid} experiment trace parity ok: idx={got_idx.tolist()}",
      flush=True)
"""


def test_two_process_sharded_experiment_trace_parity(tmp_path):
    """The FULL CODA experiment (scan + vmapped seeds + incremental cache)
    running SPMD across two OS processes — (H, N, C) sharded over a global
    4-device data mesh — must reproduce the single-process trace. This is
    the multi-host analog of dryrun_multichip, through the real
    jax.distributed runtime."""
    outs = _run_two_workers(_EXPERIMENT_WORKER, tmp_path)
    for pid, out in enumerate(outs):
        assert f"worker {pid} experiment trace parity ok" in out
