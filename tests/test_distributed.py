"""Multi-process distributed backend smoke test.

Launches two real OS processes that join one ``jax.distributed`` runtime
through ``coda_tpu.parallel.distributed.initialize`` (CPU backend, one
virtual device each) and run a cross-process psum — catching coordinator
env-var/API drift that the in-process no-op path can't
(``parallel/distributed.py:28-55``; SURVEY.md §5 distributed comm backend).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")  # site hook may register axon
sys.path.insert(0, os.environ["CODA_REPO"])
from coda_tpu.parallel.distributed import initialize, is_primary

pid = int(sys.argv[1])
ok = initialize(coordinator_address=os.environ["COORD"],
                num_processes=2, process_id=pid)
assert ok, "initialize returned False in a 2-process config"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()
assert jax.local_device_count() == 1
assert is_primary() == (pid == 0)

import jax.numpy as jnp

# one local device per process; pmap's axis spans all GLOBAL devices, so the
# psum crosses the process boundary through the distributed runtime
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.asarray([float(pid + 1)])
)
assert float(out[0]) == 3.0, float(out[0])
print(f"worker {pid} psum ok", flush=True)
"""


def test_two_process_psum(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env["COORD"] = f"127.0.0.1:{port}"
    env["CODA_REPO"] = os.path.join(os.path.dirname(__file__), "..")
    env.pop("JAX_COORDINATOR", None)
    procs = [
        subprocess.Popen([sys.executable, str(worker), str(pid)], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=150)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"worker {pid} psum ok" in out


def test_single_process_is_noop(monkeypatch):
    from coda_tpu.parallel.distributed import initialize

    monkeypatch.delenv("JAX_COORDINATOR", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize() is False
