import numpy as np
import jax
import jax.numpy as jnp
import pytest

from coda_tpu.data import make_synthetic_task
from coda_tpu.engine import run_experiment, run_seeds
from coda_tpu.engine.loop import build_experiment_fn
from coda_tpu.oracle import true_losses
from coda_tpu.selectors import (
    CODAHyperparams,
    SELECTOR_FACTORIES,
    make_coda,
    make_iid,
    make_modelpicker,
    make_uncertainty,
)
from coda_tpu.selectors.activetesting import lure_risks, surrogate_expected_losses
from coda_tpu.selectors.coda import eig_scores, update_pi_hat, _disagreement_mask
from coda_tpu.selectors.vma import pairwise_absdiff_sum, vma_scores

ITERS = 10


@pytest.fixture(scope="module")
def task():
    return make_synthetic_task(seed=11, H=6, N=64, C=4)


def _make(name, task):
    preds = task.preds
    if name == "coda":
        # small grid + single-batch map: cheap to compile, same code paths
        return make_coda(preds, CODAHyperparams(eig_chunk=64, num_points=64))
    if name in ("activetesting", "vma"):
        return SELECTOR_FACTORIES[name](preds, budget=ITERS)
    return SELECTOR_FACTORIES[name](preds)


@pytest.fixture(scope="module")
def results(task):
    """One compiled experiment per method, shared by the assertions below."""
    out = {}
    for name in sorted(SELECTOR_FACTORIES):
        sel = _make(name, task)
        out[name] = (sel, run_experiment(sel, task, iters=ITERS, seed=0))
    return out


@pytest.mark.parametrize("name", sorted(SELECTOR_FACTORIES))
def test_selector_end_to_end(name, task, results):
    _, res = results[name]
    H, N, C = task.shape
    idxs = np.asarray(res.chosen_idx)
    # never label the same point twice
    assert len(set(idxs.tolist())) == ITERS
    assert np.all((0 <= idxs) & (idxs < N))
    # labels match the oracle
    np.testing.assert_array_equal(
        np.asarray(res.true_class), np.asarray(task.labels)[idxs]
    )
    # regrets are valid and cumulative is the running sum
    regrets = np.asarray(res.regret)
    assert np.all(regrets >= -1e-6)
    np.testing.assert_allclose(
        np.asarray(res.cumulative_regret), np.cumsum(regrets), atol=1e-5
    )
    assert np.all((0 <= np.asarray(res.best_model)) & (np.asarray(res.best_model) < H))


def test_experiment_deterministic_given_seed(task):
    sel = make_iid(task.preds)
    losses = true_losses(task.preds, task.labels)
    fn = jax.jit(build_experiment_fn(sel, task.labels, losses, iters=6))
    r1 = fn(jax.random.PRNGKey(3))
    r2 = fn(jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(r1.chosen_idx), np.asarray(r2.chosen_idx))
    r3 = fn(jax.random.PRNGKey(4))
    assert not np.array_equal(np.asarray(r1.chosen_idx), np.asarray(r3.chosen_idx))


def test_coda_converges_and_beats_iid():
    """On an easy task CODA finds the best model; cum regret <= IID's."""
    task = make_synthetic_task(seed=5, H=5, N=80, C=4, acc_lo=0.3, acc_hi=0.95)
    iters = 16
    coda_res = run_experiment(
        make_coda(task.preds, CODAHyperparams(eig_chunk=80, num_points=64)),
        task, iters=iters, seed=0,
    )
    iid_res = run_seeds(make_iid(task.preds), task, iters=iters, seeds=3)
    losses = np.asarray(true_losses(task.preds, task.labels))
    assert np.asarray(coda_res.regret)[-3:].max() < 0.05
    assert np.asarray(coda_res.best_model)[-1] == losses.argmin()
    coda_cum = float(np.asarray(coda_res.cumulative_regret)[-1])
    iid_cum = float(np.asarray(iid_res.cumulative_regret)[:, -1].mean())
    assert coda_cum <= iid_cum + 1e-6


def test_run_seeds_batches(task):
    res = run_seeds(make_iid(task.preds), task, iters=6, seeds=4)
    assert np.asarray(res.chosen_idx).shape == (4, 6)
    # different seeds make different random choices
    seqs = {tuple(np.asarray(res.chosen_idx)[s]) for s in range(4)}
    assert len(seqs) > 1


def test_run_seeds_compiled_matches_run_seeds(task):
    """The preds-as-argument compile path must equal the closure path
    bit-for-bit (same program, different constant handling)."""
    from coda_tpu.engine import run_seeds_compiled
    from coda_tpu.selectors import CODAHyperparams, make_coda

    hp = CODAHyperparams(eig_chunk=16)
    want = run_seeds(make_coda(task.preds, hp), task, iters=5, seeds=2)
    got = run_seeds_compiled(lambda p: make_coda(p, hp), task.preds,
                             task.labels, iters=5, seeds=2)
    for name in want._fields:
        a, b = np.asarray(getattr(want, name)), np.asarray(getattr(got, name))
        if a.dtype.kind == "f":
            # constant-folding vs runtime parameters reorders a few fused
            # ops; traces must agree, float scores only to epsilon
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6,
                                       err_msg=name)
        else:
            np.testing.assert_array_equal(a, b, err_msg=name)


def test_uncertainty_picks_highest_entropy(task, results):
    _, res = results["uncertainty"]
    from coda_tpu.selectors.uncertainty import uncertainty_scores

    scores = np.asarray(uncertainty_scores(task.preds))
    order = np.argsort(-scores)
    # without ties, picks are the top-entropy points in order
    np.testing.assert_array_equal(np.asarray(res.chosen_idx), order[:ITERS])
    # note: the run may still be stochastic via best-model risk ties


def test_pi_hat_properties(task):
    from coda_tpu.ops.confusion import (
        create_confusion_matrices,
        ensemble_preds,
        initialize_dirichlets,
    )

    ens_hard = ensemble_preds(task.preds).argmax(-1)
    soft = create_confusion_matrices(ens_hard, task.preds, mode="soft")
    d = 2.0 * initialize_dirichlets(soft, 0.1)
    pi_xi, pi = update_pi_hat(d, task.preds)
    H, N, C = task.shape
    assert pi_xi.shape == (N, C) and pi.shape == (C,)
    np.testing.assert_allclose(np.asarray(pi_xi).sum(-1), 1.0, atol=1e-5)
    assert float(np.asarray(pi).sum()) == pytest.approx(1.0, abs=1e-5)


def test_eig_chunk_invariance_finite_nonneg(task, results):
    sel, _ = results["coda"]
    state = sel.init(jax.random.PRNGKey(0))
    hard_preds = task.preds.argmax(-1).T.astype(jnp.int32)
    e1 = np.asarray(eig_scores(state.dirichlets, state.pi_hat, state.pi_hat_xi,
                               hard_preds, num_points=64, chunk=7))
    e2 = np.asarray(eig_scores(state.dirichlets, state.pi_hat, state.pi_hat_xi,
                               hard_preds, num_points=64, chunk=64))
    # different batch sizes change XLA fusion/reduction order -> fp32 noise
    np.testing.assert_allclose(e1, e2, rtol=1e-4, atol=1e-6)
    assert np.all(np.isfinite(e1))
    assert e1.min() > -1e-4 and e1.max() > 0


def test_disagreement_mask(task):
    hard = np.asarray(task.preds.argmax(-1)).T  # (N, H)
    mask = np.asarray(_disagreement_mask(jnp.asarray(hard), task.shape[2]))
    for n in range(task.shape[1]):
        vals, counts = np.unique(hard[n], return_counts=True)
        # majority ties resolve to the smallest class in both implementations
        maj = vals[counts == counts.max()].min()
        expected = bool((hard[n] != maj).sum() > 0)
        assert mask[n] == expected


def test_coda_prefilter_n_subsamples():
    task = make_synthetic_task(seed=2, H=4, N=32, C=3)
    sel = make_coda(task.preds, CODAHyperparams(prefilter_n=8, eig_chunk=32,
                                                num_points=32))
    res = run_experiment(sel, task, iters=3, seed=0)
    assert bool(res.stochastic)


def test_surrogate_expected_losses(task):
    sl = np.asarray(surrogate_expected_losses(task.preds))
    p = np.asarray(task.preds)
    ens = p.mean(0)
    H, N, C = p.shape
    manual = np.empty((H, N), np.float32)
    for h in range(H):
        manual[h] = 1.0 - ens[np.arange(N), p[h].argmax(-1)]
    np.testing.assert_allclose(sl, manual, rtol=1e-6)


def test_lure_weights_match_reference_formula():
    """v_m = 1 + (N-M)/(N-m) * (1/((N-m+1) q_m) - 1), risk = mean(v*loss)."""
    rng = np.random.default_rng(0)
    N, H, T, M = 50, 3, 8, 5
    losses = rng.uniform(0, 1, (H, T)).astype(np.float32)
    losses[:, M:] = 0.0
    qs = rng.uniform(0.01, 0.2, T).astype(np.float32)
    risks = np.asarray(lure_risks(jnp.asarray(losses), jnp.asarray(qs),
                                  jnp.asarray(M), N))
    manual_v = [
        1 + ((N - M) / (N - m)) * (1 / ((N - m + 1) * qs[m - 1]) - 1)
        for m in range(1, M + 1)
    ]
    manual = (np.asarray(manual_v)[None, :] * losses[:, :M]).mean(1)
    np.testing.assert_allclose(risks, manual, rtol=1e-5)


def test_pairwise_absdiff_sorted_identity():
    rng = np.random.default_rng(4)
    v = rng.uniform(0, 1, size=(7, 20)).astype(np.float32)
    ours = np.asarray(pairwise_absdiff_sum(jnp.asarray(v), axis=0))
    manual = np.zeros(20, np.float32)
    for i in range(7):
        for j in range(i + 1, 7):
            manual += np.abs(v[i] - v[j])
    np.testing.assert_allclose(ours, manual, rtol=1e-4, atol=1e-5)


def test_vma_scores_match_bruteforce(task):
    scores = np.asarray(vma_scores(task.preds))
    losses = np.asarray(surrogate_expected_losses(task.preds))
    H = losses.shape[0]
    manual = np.zeros(losses.shape[1], np.float32)
    for i in range(H):
        for j in range(i + 1, H):
            manual += np.abs(losses[i] - losses[j])
    np.testing.assert_allclose(scores, manual, rtol=1e-4, atol=1e-5)


def test_modelpicker_posterior_update(task):
    sel = make_modelpicker(task.preds, epsilon=0.4)
    state = sel.init(jax.random.PRNGKey(0))
    gamma = 0.6 / 0.4
    idx, tc = 3, int(task.labels[3])
    new_state = sel.update(state, jnp.asarray(idx), jnp.asarray(tc), jnp.asarray(0.0))
    hard = np.asarray(task.preds.argmax(-1))  # (H, N)
    agree = (hard[:, idx] == tc).astype(np.float64)
    manual = np.asarray(state.posterior) * gamma**agree
    manual /= manual.sum()
    np.testing.assert_allclose(np.asarray(new_state.posterior), manual, rtol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(new_state.correct_counts), agree.astype(np.int64)
    )


def test_modelpicker_prefers_disagreement(task, results):
    hard = np.asarray(task.preds.argmax(-1)).T  # (N, H)
    disagree = (hard != hard[:, :1]).any(1)
    _, res = results["model_picker"]
    if disagree.any():
        assert disagree[np.asarray(res.chosen_idx)].all()


def test_budget_guard_raises(task):
    from coda_tpu.selectors import make_activetesting

    sel = make_activetesting(task.preds, budget=4)
    with pytest.raises(ValueError, match="budget"):
        run_experiment(sel, task, iters=10, seed=0)


def test_best_model_tie_randomness_marks_stochastic():
    """Two identical models force best-model risk ties -> stochastic=True
    even for the deterministic uncertainty selector (reference iid.py
    get_best_model_prediction sets the flag on ties)."""
    base = make_synthetic_task(seed=3, H=3, N=40, C=4)
    preds = np.array(base.preds)  # writable copy
    preds[1] = preds[0]  # duplicate model 0 -> permanent risk tie
    from coda_tpu.data import Dataset

    dup = Dataset(preds=jnp.asarray(preds), labels=base.labels, name="dup")
    res = run_experiment(make_uncertainty(dup.preds), dup, iters=4, seed=0)
    assert bool(res.stochastic)


def test_iters_exceeding_n_raises(task):
    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import make_iid

    sel = make_iid(task.preds)
    with pytest.raises(ValueError, match="exceeds"):
        run_experiment(sel, task, iters=task.preds.shape[1] + 1)


def test_coda_prefilter_fallback_scores_all_unlabeled():
    """Once every disagreement point is labeled, the prefilter must NOT
    subsample the all-agreement fallback pool (reference coda/coda.py:239)."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import CODAState, _disagreement_mask

    t = make_synthetic_task(seed=5, H=4, N=24, C=3)
    sel = make_coda(t.preds, CODAHyperparams(prefilter_n=4, eig_chunk=24))
    state = sel.init(jax.random.PRNGKey(0))
    # label every disagreement point -> fallback pool = remaining unlabeled
    hard = jnp.argmax(t.preds, -1).T
    disagree = _disagreement_mask(hard, 3)
    state = CODAState(
        dirichlets=state.dirichlets,
        pi_hat_xi=state.pi_hat_xi,
        pi_hat=state.pi_hat,
        unlabeled=state.unlabeled & ~disagree,
    )
    n_pool = int(state.unlabeled.sum())
    assert n_pool > 4  # bigger than prefilter_n: would be subsampled if buggy
    picks = set()
    for s in range(12):
        res = sel.select(state, jax.random.PRNGKey(s))
        assert not bool(res.stochastic)  # fallback is deterministic greedy
        picks.add(int(res.idx))
    assert len(picks) == 1  # greedy over the full pool: always the same point


def test_coda_incremental_matches_factored_trace(task):
    """The incremental EIG (cached per-class P(best), row-refresh updates)
    must reproduce the stateless factored kernel's full experiment trace."""
    import jax

    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda

    runs = {}
    for mode in ("factored", "incremental"):
        sel = make_coda(task.preds, CODAHyperparams(eig_mode=mode,
                                                    eig_chunk=32))
        runs[mode] = run_experiment(sel, task, iters=12, seed=0)
    fac, inc = runs["factored"], runs["incremental"]
    assert np.asarray(fac.chosen_idx).tolist() == \
        np.asarray(inc.chosen_idx).tolist()
    assert np.asarray(fac.best_model).tolist() == \
        np.asarray(inc.best_model).tolist()
    np.testing.assert_allclose(np.asarray(fac.select_prob),
                               np.asarray(inc.select_prob),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(fac.regret),
                                  np.asarray(inc.regret))


def test_coda_incremental_cache_row_refresh_exact(task):
    """After an update, the incrementally-refreshed cache must equal a cache
    rebuilt from scratch: the refreshed row matches bit-for-bit in structure
    (same kernel) and the untouched rows carry over unchanged."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import build_eig_cache

    sel = make_coda(task.preds, CODAHyperparams(eig_mode="incremental",
                                                eig_chunk=1000))
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    labels = np.asarray(task.labels)
    hard = jnp.argmax(task.preds, -1).T.astype(jnp.int32)
    update = jax.jit(sel.update)

    for idx in (3, 11, 7):
        tc = int(labels[idx])
        prev_hyp = np.asarray(state.pbest_hyp)
        state = update(state, jnp.asarray(idx), jnp.asarray(tc),
                       jnp.asarray(0.0))
        rows_full, hyp_full = jax.jit(
            lambda d: build_eig_cache(d, hard, chunk=1000)
        )(state.dirichlets)
        np.testing.assert_allclose(np.asarray(state.pbest_rows),
                                   np.asarray(rows_full),
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(np.asarray(state.pbest_hyp),
                                   np.asarray(hyp_full),
                                   rtol=1e-5, atol=1e-7)
        # untouched class rows are carried over bitwise ((C, N, H) layout:
        # class rows lead)
        untouched = [c for c in range(task.preds.shape[2]) if c != tc]
        np.testing.assert_array_equal(
            np.asarray(state.pbest_hyp)[untouched],
            prev_hyp[untouched])


def test_coda_auto_mode_resolution():
    """auto -> incremental for plain full-pool EIG; factored when the
    prefilter subsamples or the acquisition isn't EIG."""
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.selectors import CODAHyperparams, make_coda

    t = make_synthetic_task(seed=2, H=4, N=32, C=3)

    def cache_of(hp):
        sel = make_coda(t.preds, hp)
        return jax.jit(sel.init)(jax.random.PRNGKey(0)).pbest_hyp

    assert cache_of(CODAHyperparams()) is not None
    assert cache_of(CODAHyperparams(prefilter_n=8)) is None
    assert cache_of(CODAHyperparams(q="iid")) is None
    assert cache_of(CODAHyperparams(eig_mode="factored")) is None
    # explicit incremental with an acquisition that never reads the cache
    # is a config error, not silent dead work
    with pytest.raises(ValueError, match="full-pool EIG"):
        make_coda(t.preds, CODAHyperparams(eig_mode="incremental", q="iid"))
    with pytest.raises(ValueError, match="full-pool EIG"):
        make_coda(t.preds, CODAHyperparams(eig_mode="incremental",
                                           prefilter_n=8))


def test_modelpicker_static_trim_matches_full_scoring(task):
    """The static disagreement-set trim must produce the same entropy vector
    (trimmed points get exactly the posterior's entropy) and the same
    experiment trace as scoring every point."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.engine import run_experiment
    from coda_tpu.ops.masked import entropy2
    from coda_tpu.selectors.modelpicker import (
        expected_entropies, make_modelpicker,
    )

    sel = make_modelpicker(task.preds, epsilon=0.44)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    hard = jnp.argmax(task.preds, -1).T.astype(jnp.int32)
    full = np.asarray(expected_entropies(hard, state.posterior,
                                         (1 - 0.44) / 0.44,
                                         task.preds.shape[2]))
    agree = ~np.asarray((hard != hard[:, :1]).any(axis=1))
    assert agree.any() and not agree.all()
    # at full-agreement points, full scoring equals the posterior's entropy
    # (same math through the bucketed closed form, so equal only up to
    # float accumulation order — and identical ACROSS agreement points,
    # which is what keeps the trim's tie semantics exact)
    np.testing.assert_allclose(
        full[agree], float(entropy2(state.posterior)), rtol=0, atol=1e-6)
    # agreement points follow the same arithmetic up to the position of the
    # consensus column in the class mean, so they agree to ~ulp (the trim
    # path substitutes ONE shared scalar, which is what makes its tie
    # semantics exact by construction)
    assert np.ptp(full[agree]) <= 5e-7

    # trace of the trimmed selector == trace of a forced-full-scoring run
    # (tracer path: build the selector inside jit via a preds argument)
    from coda_tpu.engine.loop import make_batched_experiment_fn

    keys = jnp.stack([jax.random.PRNGKey(0)])
    fn = make_batched_experiment_fn(
        lambda p: make_modelpicker(p, epsilon=0.44), iters=10)
    res_traced = jax.jit(fn)(task.preds, task.labels, keys)
    res_static = run_experiment(sel, task, iters=10, seed=0)
    np.testing.assert_array_equal(np.asarray(res_traced.chosen_idx)[0],
                                  np.asarray(res_static.chosen_idx))
    np.testing.assert_array_equal(np.asarray(res_traced.best_model)[0],
                                  np.asarray(res_static.best_model))


def test_coda_rowscan_matches_factored(task):
    """The class-row-scanned EIG (large-C memory tier) must match the
    factored kernel's scores to fp32 accumulation noise and reproduce its
    experiment trace."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import (
        eig_scores_factored, eig_scores_rowscan,
    )

    sel = make_coda(task.preds, CODAHyperparams(eig_mode="factored",
                                                eig_chunk=16))
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    hard = jnp.argmax(task.preds, -1).T.astype(jnp.int32)
    f = np.asarray(jax.jit(lambda s: eig_scores_factored(
        s.dirichlets, s.pi_hat, s.pi_hat_xi, hard, chunk=16))(state))
    r = np.asarray(jax.jit(lambda s: eig_scores_rowscan(
        s.dirichlets, s.pi_hat, s.pi_hat_xi, hard, chunk=16))(state))
    np.testing.assert_allclose(f, r, rtol=1e-2, atol=1e-6)
    assert int(f.argmax()) == int(r.argmax())

    res_f = run_experiment(make_coda(task.preds, CODAHyperparams(
        eig_mode="factored", eig_chunk=16)), task, iters=10, seed=0)
    res_r = run_experiment(make_coda(task.preds, CODAHyperparams(
        eig_mode="rowscan", eig_chunk=16)), task, iters=10, seed=0)
    np.testing.assert_array_equal(np.asarray(res_f.chosen_idx),
                                  np.asarray(res_r.chosen_idx))
    np.testing.assert_array_equal(np.asarray(res_f.best_model),
                                  np.asarray(res_r.best_model))


def test_coda_incremental_pi_hat_column_exact(task):
    """The single-column pi-hat refresh must equal the full einsum: columns
    c != true_class are carried bitwise, the refreshed column and the
    normalized posteriors match the full recompute."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import update_pi_hat

    sel = make_coda(task.preds, CODAHyperparams(eig_mode="incremental",
                                                eig_chunk=1000))
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    labels = np.asarray(task.labels)
    update = jax.jit(sel.update)
    full_j = jax.jit(lambda d: update_pi_hat(d, task.preds))

    for idx in (4, 19, 2, 31):
        state = update(state, jnp.asarray(idx),
                       jnp.asarray(int(labels[idx])), jnp.asarray(0.0))
        pi_xi_full, pi_full = full_j(state.dirichlets)
        np.testing.assert_allclose(np.asarray(state.pi_hat_xi),
                                   np.asarray(pi_xi_full),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(state.pi_hat),
                                   np.asarray(pi_full), rtol=1e-6, atol=1e-7)


def test_eig_precision_plumbing():
    """All precision tiers must run (CPU ignores matmul precision, so
    traces are identical here — the knob's numeric effect is TPU-only and
    documented as an opt-in parity tradeoff); unknown names fail loudly."""
    import pytest

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = make_synthetic_task(seed=11, H=5, N=48, C=4)
    traces = []
    for prec in ("highest", "high", "default"):
        res = run_experiment(
            make_coda(task.preds, CODAHyperparams(eig_precision=prec)),
            task, iters=5, seed=0)
        traces.append(np.asarray(res.chosen_idx).tolist())
    assert traces[0] == traces[1] == traces[2]  # CPU: bitwise identical

    for mode in ("factored", "rowscan"):
        res = run_experiment(
            make_coda(task.preds, CODAHyperparams(eig_precision="high",
                                                  eig_mode=mode)),
            task, iters=3, seed=0)
        assert np.isfinite(np.asarray(res.regret)).all()

    with pytest.raises(ValueError, match="eig_precision"):
        make_coda(task.preds, CODAHyperparams(eig_precision="bf16"))


def test_eig_precision_direct_mode_rejected():
    import pytest

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = make_synthetic_task(seed=11, H=4, N=24, C=3)
    with pytest.raises(ValueError, match="direct"):
        make_coda(task.preds, CODAHyperparams(eig_mode="direct",
                                              eig_precision="high"))


def test_auto_eig_mode_accounts_for_vmapped_replicas():
    """The 'auto' tier budget is per-chip, not per-replica: a shape whose
    single cache fits must fall back to the stateless factored kernel when
    vmapped seeds would carry several caches at once."""
    from coda_tpu.selectors import CODAHyperparams
    from coda_tpu.selectors.coda import (
        _INCR_CACHE_MAX_BYTES,
        resolve_eig_mode,
    )

    H, C = 1000, 10
    # the delta pi-hat default carries TWO preds-sized tensors (cache +
    # transposed layout) plus the dense (H, C, C) posterior itself, so one
    # replica is budgeted at 2 copies + the posterior; size the cache just
    # under (budget - posterior)/2
    budget = _INCR_CACHE_MAX_BYTES - 4 * H * C * C
    N = budget // (2 * 4 * C * H) - 1
    assert resolve_eig_mode(CODAHyperparams(), H, N, C) == "incremental"
    assert resolve_eig_mode(
        CODAHyperparams(n_parallel=5), H, N, C) == "factored"
    # pi_update='exact' keeps only the cache resident: twice the N fits
    N2 = budget // (4 * C * H) - 1
    assert resolve_eig_mode(
        CODAHyperparams(pi_update="exact"), H, N2, C) == "incremental"
    assert resolve_eig_mode(CODAHyperparams(), H, N2, C) == "factored"
    # explicit mode is never overridden by the budget
    assert resolve_eig_mode(
        CODAHyperparams(n_parallel=5, eig_mode="incremental"), H, N, C
    ) == "incremental"


def test_pi_delta_matches_exact_recompute(task):
    """The bandwidth-lean delta pi-hat path (pi_update='delta', the
    incremental default) must track the exact column recompute over a LONG
    run: same selection/best trace on this (non-degenerate) task, and the
    accumulated unnormalized cache must stay within float-drift tolerance
    of a from-scratch recompute after every round."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import pi_unnorm

    from coda_tpu.data import make_synthetic_task

    # a dedicated task big enough for the FULL reference experiment length
    task = make_synthetic_task(seed=3, H=8, N=200, C=4)
    iters = 100
    res = {}
    for mode in ("delta", "exact"):
        sel = make_coda(task.preds, CODAHyperparams(
            eig_mode="incremental", eig_chunk=1000, pi_update=mode))
        res[mode] = run_experiment(sel, task, iters=iters, seed=0)
    np.testing.assert_array_equal(np.asarray(res["delta"].chosen_idx),
                                  np.asarray(res["exact"].chosen_idx))
    np.testing.assert_array_equal(np.asarray(res["delta"].best_model),
                                  np.asarray(res["exact"].best_model))

    # drift bound after 100 accumulated deltas: replay the delta run's state
    # and compare its unnorm cache to a from-scratch contraction
    sel = make_coda(task.preds, CODAHyperparams(
        eig_mode="incremental", eig_chunk=1000, pi_update="delta"))
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    update = jax.jit(sel.update)
    labels = np.asarray(task.labels)
    for idx in np.asarray(res["delta"].chosen_idx):
        state = update(state, jnp.asarray(int(idx)),
                       jnp.asarray(int(labels[idx])), jnp.asarray(0.0))
    fresh = pi_unnorm(state.dirichlets, task.preds)
    np.testing.assert_allclose(np.asarray(state.pi_xi_unnorm),
                               np.asarray(fresh), rtol=2e-5, atol=1e-6)


def test_bf16_cache_scores_and_budget(task):
    """eig_cache_dtype='bfloat16': (a) the cache is stored bf16 and scores
    stay within bf16 quantization of the fp32 path (math is fp32 after
    upcast); (b) the auto budget charges half the cache bytes; (c) the
    pallas backend reads the bf16 cache too (in-kernel upcast) and its
    interpret-mode scores match the jnp path."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import (
        _INCR_CACHE_MAX_BYTES,
        eig_scores_from_cache,
        resolve_eig_mode,
    )

    states = {}
    for dt in ("float32", "bfloat16"):
        sel = make_coda(task.preds, CODAHyperparams(
            eig_mode="incremental", eig_chunk=1000, eig_cache_dtype=dt))
        states[dt] = jax.jit(sel.init)(jax.random.PRNGKey(0))
    assert states["bfloat16"].pbest_hyp.dtype == jnp.bfloat16
    assert states["float32"].pbest_hyp.dtype == jnp.float32

    s32 = np.asarray(eig_scores_from_cache(
        states["float32"].pbest_rows, states["float32"].pbest_hyp,
        states["float32"].pi_hat, states["float32"].pi_hat_xi))
    s16 = np.asarray(eig_scores_from_cache(
        states["bfloat16"].pbest_rows, states["bfloat16"].pbest_hyp,
        states["bfloat16"].pi_hat, states["bfloat16"].pi_hat_xi))
    # stored probabilities carry ~2^-8 relative error; entropies are O(log H)
    assert np.max(np.abs(s32 - s16)) < 0.05
    # the ordering signal survives quantization on a non-degenerate task:
    # the fp32 top pick stays in the bf16 top-5
    assert int(s32.argmax()) in np.argsort(s16)[-5:]

    # budget: with the exact pi path (no delta layout), a bf16 cache fits
    # at TWICE the N the fp32 cache does (net of the dense posterior's own
    # resident charge)
    H, C = 1000, 10
    n_fp32 = (_INCR_CACHE_MAX_BYTES - 4 * H * C * C) // (4 * C * H) - 1
    assert resolve_eig_mode(CODAHyperparams(
        pi_update="exact"), H, 2 * n_fp32, C) == "factored"
    assert resolve_eig_mode(CODAHyperparams(
        pi_update="exact", eig_cache_dtype="bfloat16"),
        H, 2 * n_fp32, C) == "incremental"

    # the pallas backend reads the bf16 cache too (upcast in-kernel):
    # interpret-mode scores must match the jnp path on the same state
    from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas

    st = states["bfloat16"]
    s_pl = np.asarray(eig_scores_cache_pallas(
        st.pbest_rows, st.pbest_hyp, st.pi_hat, st.pi_hat_xi))
    np.testing.assert_allclose(s_pl, s16, rtol=1e-5, atol=1e-6)


def test_modelpicker_bucket_impls_agree():
    """The scatter (CPU) and scan (TPU) bucket lowerings compute the same
    t1/t2 sums — including under the suite's task x seed double vmap, the
    configuration whose scatter lowering crashed the TPU worker."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors.modelpicker import _bucket_sums

    key = jax.random.PRNGKey(0)
    N, H, C = 200, 7, 11
    hard = jax.random.randint(key, (N, H), 0, C).astype(jnp.int32)
    w = jax.random.uniform(jax.random.PRNGKey(1), (H,)) + 0.01
    wlw = w * jnp.log(w)
    t1_a, t2_a = _bucket_sums(hard, w, wlw, C, impl="scatter")
    t1_b, t2_b = _bucket_sums(hard, w, wlw, C, impl="scan")
    np.testing.assert_allclose(np.asarray(t1_a), np.asarray(t1_b),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(t2_a), np.asarray(t2_b),
                               rtol=1e-6, atol=1e-7)

    # double-vmapped (T, S) batch of posteriors over one prediction table
    T, S = 2, 3
    ws = jax.random.uniform(jax.random.PRNGKey(2), (T, S, H)) + 0.01
    f = lambda impl: jax.vmap(jax.vmap(
        lambda w_: _bucket_sums(hard, w_, w_ * jnp.log(w_), C, impl=impl)
    ))(ws)
    for a, b in zip(f("scatter"), f("scan")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_eig_scores_from_cache_vmap_ragged_chunk():
    """Vmapped scoring with a chunk that does NOT divide N must equal the
    per-replica computation. The ragged final block's start is clamped
    explicitly: under vmap the dynamic slice lowers to a gather, and
    out-of-bounds gather indices are implementation-defined on TPU — the
    unclamped version read garbage there (v5e, round 5) while passing on
    CPU, so this test guards the clamp's presence, and on TPU runs it
    guards the actual behavior."""
    import jax
    import jax.numpy as jnp

    from coda_tpu.selectors.coda import eig_scores_from_cache

    S, N, C, H = 3, 100, 4, 6   # chunk 32 -> 4 blocks, ragged tail of 4
    ks = jax.random.split(jax.random.PRNGKey(5), 4)
    rows = jax.nn.softmax(jax.random.normal(ks[0], (S, C, H)), axis=-1)
    hyp = jax.nn.softmax(jax.random.normal(ks[1], (S, C, N, H)), axis=-1)
    pi = jax.nn.softmax(jax.random.normal(ks[2], (S, C)), axis=-1)
    pi_xi = jax.nn.softmax(jax.random.normal(ks[3], (S, N, C)), axis=-1)
    vm = jax.jit(jax.vmap(
        lambda r, h, p, px: eig_scores_from_cache(r, h, p, px, chunk=32)))(
        rows, hyp, pi, pi_xi)
    for s in range(S):
        ref = eig_scores_from_cache(rows[s], hyp[s], pi[s], pi_xi[s],
                                    chunk=32)
        np.testing.assert_allclose(np.asarray(vm[s]), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6, err_msg=str(s))


def test_streamed_pi_contraction_matches_einsum(monkeypatch):
    """Past the one-shot budget the pi/confusion contractions demote to
    DEFAULT matmul precision (no HIGH/HIGHEST contraction of a ~10 GiB
    operand compiles on the TPU stack); the einsum FORM is unchanged, so
    on the CPU test backend (fp32 either way) results match the HIGHEST
    path exactly — this pins that the demotion changes nothing else.
    The demotion is gated on the backend that forces it, so exercising it
    here widens the gate to the CPU test backend."""
    import coda_tpu.ops.confusion as confusion
    import coda_tpu.selectors.coda as coda_mod
    from coda_tpu.selectors.coda import (
        pi_unnorm,
        update_pi_hat_column,
    )

    H, N, C = 6, 50, 4
    key = jax.random.PRNGKey(9)
    preds = jax.nn.softmax(jax.random.normal(key, (H, N, C)), axis=-1)
    dirichlets = jax.random.uniform(
        jax.random.PRNGKey(10), (H, C, C)) * 2 + 0.5
    ref_unnorm = pi_unnorm(dirichlets, preds)
    ens = jnp.zeros((N,), jnp.int32)
    from coda_tpu.ops.confusion import create_confusion_matrices
    ref_conf = create_confusion_matrices(ens, preds, mode="soft")
    ref_col = update_pi_hat_column(dirichlets, jnp.int32(1), preds,
                                   ref_unnorm)

    monkeypatch.setattr(confusion, "PREDS_ONESHOT_MAX_BYTES", 1)
    monkeypatch.setattr(confusion, "_DEMOTE_BACKENDS", ("cpu", "tpu"))
    monkeypatch.setattr(confusion, "_warned_demotion", False)
    with pytest.warns(UserWarning, match="one-shot"):
        out_unnorm = pi_unnorm(dirichlets, preds)
    out_conf = create_confusion_matrices(ens, preds, mode="soft")
    out_col = update_pi_hat_column(dirichlets, jnp.int32(1), preds,
                                   ref_unnorm)
    np.testing.assert_allclose(np.asarray(ref_unnorm),
                               np.asarray(out_unnorm), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ref_conf),
                               np.asarray(out_conf), rtol=1e-5)
    for a, b in zip(ref_col, out_col):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5)


def test_oneshot_demotion_gated_on_backend(monkeypatch):
    """The automatic precision demotion is scoped to the TPU backend that
    cannot compile the HIGHEST contraction (ADVICE round 5): on the CPU
    test backend an over-budget operand keeps HIGHEST, and the one-time
    warning fires only when the demotion actually engages."""
    import warnings as _warnings

    import coda_tpu.ops.confusion as confusion

    monkeypatch.setattr(confusion, "PREDS_ONESHOT_MAX_BYTES", 1)
    monkeypatch.setattr(confusion, "_warned_demotion", False)
    # default gate: cpu backend never demotes, never warns
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        assert (confusion.oneshot_precision(10 << 30)
                == jax.lax.Precision.HIGHEST)
    # widened gate: demotes past the budget, warns exactly once
    monkeypatch.setattr(confusion, "_DEMOTE_BACKENDS", ("cpu", "tpu"))
    with pytest.warns(UserWarning, match="compile bound"):
        assert (confusion.oneshot_precision(10 << 30)
                == jax.lax.Precision.DEFAULT)
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        assert (confusion.oneshot_precision(10 << 30)
                == jax.lax.Precision.DEFAULT)   # warned already
        assert (confusion.oneshot_precision(1) ==
                jax.lax.Precision.HIGHEST)      # in-budget stays HIGHEST
