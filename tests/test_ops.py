import numpy as np
import jax
import jax.numpy as jnp
import pytest
from scipy import stats

from coda_tpu.ops.beta import beta_log_pdf, cumtrapz_uniform, dirichlet_to_beta
from coda_tpu.ops.confusion import (
    create_confusion_matrices,
    ensemble_preds,
    initialize_dirichlets,
)
from coda_tpu.ops.masked import (
    entropy2,
    masked_argmax_tiebreak,
    masked_categorical,
)
from coda_tpu.ops.pbest import compute_pbest, pbest_grid, pbest_row_mixture


def test_dirichlet_to_beta():
    rng = np.random.default_rng(0)
    d = rng.uniform(0.5, 5.0, size=(3, 4, 4)).astype(np.float32)
    a, b = dirichlet_to_beta(jnp.asarray(d))
    a, b = np.asarray(a), np.asarray(b)
    for h in range(3):
        for c in range(4):
            assert a[h, c] == pytest.approx(d[h, c, c], rel=1e-6)
            assert b[h, c] == pytest.approx(d[h, c].sum() - d[h, c, c], rel=1e-5)


def test_beta_log_pdf_matches_scipy():
    x = np.linspace(0.01, 0.99, 50)
    for a, b in [(2.0, 3.0), (0.5, 0.5), (10.0, 1.5)]:
        ours = np.asarray(beta_log_pdf(jnp.asarray(x, jnp.float32),
                                       jnp.float32(a), jnp.float32(b)))
        ref = stats.beta.logpdf(x, a, b)
        # fp32 lgamma: small absolute error, looser near zero crossings
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=2e-3)


def test_cumtrapz_matches_serial_reference():
    """The parallel cumsum CDF must equal the reference's sequential loop."""
    rng = np.random.default_rng(1)
    pdf = rng.uniform(0.0, 3.0, size=(4, 5, 64)).astype(np.float32)
    x = np.linspace(1e-6, 1 - 1e-6, 64, dtype=np.float32)
    dx = x[1] - x[0]
    # serial accumulation exactly as reference coda/coda.py:98-101
    serial = np.zeros_like(pdf)
    for j in range(1, 64):
        serial[..., j] = serial[..., j - 1] + 0.5 * (pdf[..., j] + pdf[..., j - 1]) * dx
    ours = np.asarray(cumtrapz_uniform(jnp.asarray(pdf), dx))
    np.testing.assert_allclose(ours, serial, rtol=1e-5, atol=1e-6)


def test_cumtrapz_axis():
    y = jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4))
    out0 = cumtrapz_uniform(y, 0.5, axis=0)
    out_last = cumtrapz_uniform(y.T, 0.5, axis=-1).T
    np.testing.assert_allclose(np.asarray(out0), np.asarray(out_last), rtol=1e-6)


def test_pbest_symmetric_models():
    """Identical Betas => equal P(best)."""
    a = jnp.full((4,), 5.0)
    b = jnp.full((4,), 3.0)
    p = np.asarray(compute_pbest(a, b))
    np.testing.assert_allclose(p, 0.25, atol=1e-3)
    assert p.sum() == pytest.approx(1.0, abs=1e-5)


def test_pbest_dominant_model():
    """A clearly better Beta gets almost all the mass."""
    a = jnp.asarray([50.0, 5.0, 5.0])
    b = jnp.asarray([5.0, 50.0, 50.0])
    p = np.asarray(compute_pbest(a, b))
    assert p[0] > 0.99


def test_pbest_two_models_vs_closed_form():
    """For H=2, P(best) = P(X > Y), computable by 1-D quadrature with scipy."""
    cases = [(6.0, 4.0, 3.0, 7.0), (2.5, 2.5, 2.0, 3.0), (12.0, 3.0, 11.0, 4.0)]
    for a1, b1, a2, b2 in cases:
        p = np.asarray(compute_pbest(jnp.asarray([a1, a2]), jnp.asarray([b1, b2])))
        # P(X > Y) = ∫ pdf_X(x) * cdf_Y(x) dx on a fine grid
        x = np.linspace(1e-8, 1 - 1e-8, 20001)
        ref = np.trapezoid(stats.beta.pdf(x, a1, b1) * stats.beta.cdf(x, a2, b2), x)
        ref_norm = ref / (ref + (1 - ref))
        assert p[0] == pytest.approx(ref_norm, abs=2e-3)


def test_pbest_monte_carlo():
    rng = np.random.default_rng(7)
    a = np.array([8.0, 6.0, 3.0, 9.5], np.float32)
    b = np.array([4.0, 2.0, 3.0, 6.0], np.float32)
    p = np.asarray(compute_pbest(jnp.asarray(a), jnp.asarray(b)))
    samples = rng.beta(a[:, None], b[:, None], size=(4, 200_000))
    mc = np.bincount(samples.argmax(0), minlength=4) / samples.shape[1]
    np.testing.assert_allclose(p, mc, atol=5e-3)


def test_pbest_batched_matches_unbatched():
    rng = np.random.default_rng(3)
    a = rng.uniform(1.0, 10.0, size=(6, 4, 5)).astype(np.float32)
    b = rng.uniform(1.0, 10.0, size=(6, 4, 5)).astype(np.float32)
    batched = np.asarray(compute_pbest(jnp.asarray(a), jnp.asarray(b)))
    for i in range(6):
        for j in range(4):
            single = np.asarray(compute_pbest(jnp.asarray(a[i, j]), jnp.asarray(b[i, j])))
            np.testing.assert_allclose(batched[i, j], single, rtol=1e-5, atol=1e-7)


def test_pbest_row_mixture_uniform_pi():
    rng = np.random.default_rng(5)
    d = jnp.asarray(rng.uniform(1.0, 6.0, size=(3, 4, 4)).astype(np.float32))
    pi = jnp.full((4,), 0.25)
    mix = np.asarray(pbest_row_mixture(d, pi))
    assert mix.shape == (3,)
    # mixture of normalized distributions stays normalized
    assert mix.sum() == pytest.approx(1.0, abs=1e-4)


def test_grid_matches_reference_spec():
    x = np.asarray(pbest_grid())
    assert x.shape == (256,)
    assert x[0] == pytest.approx(1e-6)
    assert x[-1] == pytest.approx(1 - 1e-6)


def test_ensemble_and_confusion(tiny_task):
    ens = np.asarray(ensemble_preds(tiny_task.preds))
    np.testing.assert_allclose(
        ens, np.asarray(tiny_task.preds).mean(0), rtol=1e-6
    )
    pseudo = ens.argmax(-1)
    conf = np.asarray(
        create_confusion_matrices(jnp.asarray(pseudo), tiny_task.preds, mode="soft")
    )
    H, N, C = tiny_task.shape
    assert conf.shape == (H, C, C)
    np.testing.assert_allclose(conf.sum(-1), 1.0, atol=1e-4)
    hard = np.asarray(
        create_confusion_matrices(jnp.asarray(pseudo), tiny_task.preds, mode="hard")
    )
    np.testing.assert_allclose(hard.sum(-1), 1.0, atol=1e-4)


def test_confusion_hard_manual():
    # 1 model, 3 points, 2 classes: preds = [0, 1, 1], labels = [0, 1, 0]
    preds = jnp.asarray(
        [[[0.9, 0.1], [0.2, 0.8], [0.3, 0.7]]], jnp.float32
    )
    labels = jnp.asarray([0, 1, 0])
    conf = np.asarray(create_confusion_matrices(labels, preds, mode="hard"))
    # row 0 (true class 0): predictions 0 and 1 -> [0.5, 0.5]
    np.testing.assert_allclose(conf[0, 0], [0.5, 0.5], atol=1e-6)
    # row 1 (true class 1): prediction 1 -> [0, 1]
    np.testing.assert_allclose(conf[0, 1], [0.0, 1.0], atol=1e-6)


def test_initialize_dirichlets_diag_prior():
    soft = jnp.asarray(np.full((2, 4, 4), 0.25, np.float32))
    d = np.asarray(initialize_dirichlets(soft, prior_strength=0.1))
    # diag: 1.0 + 0.1*0.25 ; off-diag: 1/3 + 0.1*0.25
    np.testing.assert_allclose(np.diagonal(d, axis1=-2, axis2=-1), 1.025, rtol=1e-6)
    off = d[0, 0, 1]
    assert off == pytest.approx(1 / 3 + 0.025, rel=1e-5)
    uniform = np.asarray(initialize_dirichlets(soft, 0.1, disable_diag_prior=True))
    np.testing.assert_allclose(uniform, 2 / 4 + 0.025, rtol=1e-5)


def test_entropy2():
    p = jnp.asarray([0.5, 0.5])
    assert float(entropy2(p)) == pytest.approx(1.0, abs=1e-6)
    p = jnp.asarray([1.0, 0.0])
    assert float(entropy2(p)) == pytest.approx(0.0, abs=1e-3)


def test_masked_argmax_unique_max_deterministic():
    scores = jnp.asarray([0.1, 5.0, 3.0, 5.0])
    mask = jnp.asarray([True, True, True, False])  # the tied 5.0 is masked out
    for s in range(5):
        idx, n_ties = masked_argmax_tiebreak(jax.random.PRNGKey(s), scores, mask)
        assert int(idx) == 1
        assert int(n_ties) == 1


def test_masked_argmax_ties_uniform():
    scores = jnp.asarray([1.0, 1.0, 0.0, 1.0])
    mask = jnp.ones(4, dtype=bool)
    picks = {
        int(masked_argmax_tiebreak(jax.random.PRNGKey(s), scores, mask)[0])
        for s in range(64)
    }
    assert picks == {0, 1, 3}


def test_masked_argmax_rtol_ties():
    # in fp32, rtol=1e-8 ties exactly-equal values (adjacent floats are
    # ~1.2e-7 apart relatively) — same effective semantics as the reference,
    # which runs isclose(rtol=1e-8) on fp32 tensors
    scores = jnp.asarray([1.0, 1.0, 0.5])
    mask = jnp.ones(3, dtype=bool)
    _, n_ties = masked_argmax_tiebreak(jax.random.PRNGKey(0), scores, mask, rtol=1e-8)
    assert int(n_ties) == 2
    scores2 = jnp.asarray([1.0, 0.9999, 0.5])
    _, n2 = masked_argmax_tiebreak(jax.random.PRNGKey(0), scores2, mask, rtol=1e-8)
    assert int(n2) == 1


def test_masked_categorical_respects_mask_and_weights():
    w = jnp.asarray([10.0, 1.0, 100.0, 1.0])
    mask = jnp.asarray([True, True, False, True])
    counts = np.zeros(4)
    for s in range(300):
        idx, prob = masked_categorical(jax.random.PRNGKey(s), w, mask)
        counts[int(idx)] += 1
    assert counts[2] == 0
    assert counts[0] > counts[1]
    # reported prob is the normalized masked weight
    idx, prob = masked_categorical(jax.random.PRNGKey(0), w, mask)
    expected = np.asarray([10, 1, 0, 1], np.float32) / 12.0
    assert float(prob) == pytest.approx(expected[int(idx)], rel=1e-5)


def test_masked_categorical_degenerate_uniform_fallback():
    w = jnp.zeros(5)
    mask = jnp.asarray([True, False, True, True, False])
    for s in range(20):
        idx, prob = masked_categorical(jax.random.PRNGKey(s), w, mask)
        assert int(idx) in {0, 2, 3}
        assert float(prob) == pytest.approx(1 / 3, rel=1e-5)
