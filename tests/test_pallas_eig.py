"""The fused pallas scoring kernels must match the jnp incremental-EIG path
(interpreter mode on the CPU backend; the same kernels compile via Mosaic on
real TPUs). The cache layout is (C, N, H) — class rows leading — so the
minor dims tile onto the TPU's (8, 128) layout without the sublane padding
tax the (N, C, H) alternative pays at small C."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _random_cache(key, N, C, H):
    k1, k2, k3 = jax.random.split(key, 3)
    rows = jax.random.uniform(k1, (C, H)) + 0.1
    rows /= rows.sum(-1, keepdims=True)
    hyp = jax.random.uniform(k2, (C, N, H)) + 0.1
    hyp /= hyp.sum(-1, keepdims=True)
    pi_xi = jax.random.uniform(k3, (N, C))
    pi_xi /= pi_xi.sum(-1, keepdims=True)
    pi = pi_xi.mean(0)
    return rows, hyp, pi / pi.sum(), pi_xi


def test_pallas_scores_match_jnp_path():
    from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas
    from coda_tpu.selectors.coda import eig_scores_from_cache

    rows, hyp, pi, pi_xi = _random_cache(jax.random.PRNGKey(0), 300, 5, 12)
    ref = np.asarray(eig_scores_from_cache(rows, hyp, pi, pi_xi, chunk=64))
    pal = np.asarray(eig_scores_cache_pallas(rows, hyp, pi, pi_xi,
                                             block=64, interpret=True))
    # same integral, fused log2 -> ~1 ulp reduction noise
    np.testing.assert_allclose(ref, pal, rtol=1e-4, atol=1e-6)
    assert int(ref.argmax()) == int(pal.argmax())


def test_pallas_ragged_block_padding():
    """N not divisible by the block: padded rows must not leak into scores."""
    from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas
    from coda_tpu.selectors.coda import eig_scores_from_cache

    rows, hyp, pi, pi_xi = _random_cache(jax.random.PRNGKey(1), 77, 4, 9)
    ref = np.asarray(eig_scores_from_cache(rows, hyp, pi, pi_xi, chunk=32))
    pal = np.asarray(eig_scores_cache_pallas(rows, hyp, pi, pi_xi,
                                             block=32, interpret=True))
    assert pal.shape == (77,)
    np.testing.assert_allclose(ref, pal, rtol=1e-4, atol=1e-6)


def test_choose_block_obeys_tpu_tiling():
    """Mosaic accepts an N-tile only when it is sublane-aligned (x8 fp32 /
    x16 bf16) or spans all of N (observed lowering failure on a real v5e:
    block (100, 10) on a (50000, 10) operand). The chooser must never emit
    anything else."""
    from coda_tpu.ops.pallas_eig import choose_block

    for N, C, H, blk in [
        (50_000, 10, 1000, 2048),   # headline: vmem-capped, must align
        (50_000, 10, 1000, 0),
        (77, 4, 9, 32),             # ragged small task
        (300, 5, 12, 64),
        (64, 4, 6, 0),              # fits in one block
        (100, 1000, 500, 0),        # huge C*H: cap < 8 rows, N > cap
        (5, 3, 4, 0),               # N < 8
    ]:
        for itemsize, sub in [(4, 8), (2, 16)]:
            for fused in (False, True):
                B = choose_block(N, C, H, blk, itemsize=itemsize,
                                 fused=fused)
                assert 1 <= B <= N
                assert B == N or B % sub == 0, (N, C, H, blk, B, itemsize)


def test_choose_block_budgets_lane_padded_vmem():
    """The VMEM budget must model the PHYSICAL footprint: the (C, B, H)
    tile lane-pads H to 1024 at the headline shape and is double-buffered
    by the pipeline, the kernel's fp32 stack temporaries are charged per
    unit of B (hardware-calibrated: ignoring them put a ragged shape
    1.45 MB over the scoped limit on a v5e), and the fused kernel
    additionally pipelines the fp32 hyp_t row in and the storage-width
    refreshed row out — so its tile must be smaller than the score-only
    kernel's."""
    from coda_tpu.ops.pallas_eig import (
        _SCOPED_VMEM_BYTES,
        _TEMP_TILES,
        _VMEM_MARGIN_BYTES,
        choose_block,
    )

    C, H, Hp = 10, 1000, 1024
    budget = _SCOPED_VMEM_BYTES - _VMEM_MARGIN_BYTES
    B = choose_block(50_000, C, H)
    stream = 4 * C * Hp + 4 * 128 * C + 4 * 128
    temps = _TEMP_TILES * 4 * C * Hp
    assert B * (2 * stream + temps) <= budget
    # a temps-blind double-buffer budget would have chosen more rows
    assert B < budget // (2 * stream)
    # a logical-bytes budget (no lane padding) more still
    assert B < budget // (2 * (4 * C * H))
    B_fused = choose_block(50_000, C, H, fused=True)
    assert B_fused < B
    # bf16 storage halves the pipelined cache stream (fp32 temps remain),
    # so its tile is LARGER — the point of the eig_cache_dtype knob
    assert choose_block(50_000, C, H, itemsize=2, fused=True) > B_fused


def test_pallas_large_ch_small_tile():
    """C*H big enough that the VMEM budget allows <8 rows: the x8 minimum
    still applies and the result still matches the jnp path."""
    from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas
    from coda_tpu.selectors.coda import eig_scores_from_cache

    rows, hyp, pi, pi_xi = _random_cache(jax.random.PRNGKey(2), 13, 40, 700)
    ref = np.asarray(eig_scores_from_cache(rows, hyp, pi, pi_xi, chunk=8))
    pal = np.asarray(eig_scores_cache_pallas(rows, hyp, pi, pi_xi,
                                             interpret=True))
    # atol 1e-5, not 1e-6: the kernel's per-class unrolled elementwise
    # chain and the jnp path's batched (C, B, H) chain compile to
    # different fused FMA groupings, a ~3e-6 floor at C=40 (the same
    # magnitude measured kernel-vs-jnp on real v5e silicon in round 4)
    np.testing.assert_allclose(ref, pal, rtol=1e-4, atol=1e-5)
    assert int(ref.argmax()) == int(pal.argmax())


def test_pallas_backend_selector_trace_matches():
    """A full experiment with eig_backend='pallas' reproduces the jnp trace."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = make_synthetic_task(seed=4, H=6, N=64, C=4)
    res_j = run_experiment(
        make_coda(task.preds, CODAHyperparams(eig_mode="incremental")),
        task, iters=10, seed=0)
    res_p = run_experiment(
        make_coda(task.preds, CODAHyperparams(eig_mode="incremental",
                                              eig_backend="pallas")),
        task, iters=10, seed=0)
    np.testing.assert_array_equal(np.asarray(res_j.chosen_idx),
                                  np.asarray(res_p.chosen_idx))
    np.testing.assert_array_equal(np.asarray(res_j.best_model),
                                  np.asarray(res_p.best_model))


def test_pallas_backend_config_guards():
    import pytest

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.parallel import make_mesh, preds_sharding
    from coda_tpu.selectors import CODAHyperparams, make_coda

    t = make_synthetic_task(seed=1, H=4, N=32, C=4)
    with pytest.raises(ValueError, match="unknown eig_backend"):
        make_coda(t.preds, CODAHyperparams(eig_backend="Pallas"))
    with pytest.raises(ValueError, match="never run"):
        make_coda(t.preds, CODAHyperparams(eig_backend="pallas",
                                           eig_mode="factored"))
    if len(jax.devices()) >= 8:
        # an UNDECLARED sharded tensor still raises; declaring the mesh
        # (shard_spec) routes through the shard_map path instead
        sharded = jax.device_put(t.preds, preds_sharding(make_mesh(data=8)))
        with pytest.raises(ValueError, match="shard_spec"):
            make_coda(sharded, CODAHyperparams(eig_backend="pallas"))
        assert make_coda(sharded, CODAHyperparams(
            eig_backend="pallas", shard_spec="data=8")) is not None


def test_cli_mesh_pallas_combinations(tmp_path):
    """--mesh data=K + pallas is now the shard_map fast path; model-axis
    meshes and non-dividing N still raise (at selector build, with a
    message naming the constraint)."""
    import pytest

    from coda_tpu.cli import build_selector_factory, parse_args
    from coda_tpu.data import make_synthetic_task

    t = make_synthetic_task(seed=0, H=4, N=32, C=4)
    args = parse_args(["--synthetic", "4,32,4", "--method", "coda",
                       "--eig-backend", "pallas", "--mesh", "data=2"])
    sel = build_selector_factory(args, "synthetic")(t.preds)
    assert sel is not None

    args = parse_args(["--synthetic", "4,32,4", "--method", "coda",
                       "--eig-backend", "pallas", "--mesh", "data=2,model=2"])
    with pytest.raises(ValueError, match="DATA-only"):
        build_selector_factory(args, "synthetic")(t.preds)

    t33 = make_synthetic_task(seed=0, H=4, N=33, C=4)
    args = parse_args(["--synthetic", "4,33,4", "--method", "coda",
                       "--eig-backend", "pallas", "--mesh", "data=2"])
    with pytest.raises(ValueError, match="not divisible"):
        build_selector_factory(args, "synthetic")(t33.preds)


def test_fused_refresh_score_matches_dus_then_score():
    """The fused refresh+score kernel == DUS the new row in, then score —
    scores AND the returned cache, including a ragged final block."""
    from coda_tpu.ops.pallas_eig import eig_scores_refresh_pallas
    from coda_tpu.selectors.coda import eig_scores_from_cache

    for (N, C, H, blk) in [(300, 5, 12, 64), (77, 4, 9, 32)]:
        rows, hyp, pi, pi_xi = _random_cache(jax.random.PRNGKey(2), N, C, H)
        hyp_t = jax.random.uniform(jax.random.PRNGKey(3), (N, H)) + 0.1
        hyp_t /= hyp_t.sum(-1, keepdims=True)
        c = jnp.int32(C - 1)

        hyp_ref = hyp.at[c].set(hyp_t)
        ref = np.asarray(eig_scores_from_cache(rows, hyp_ref, pi, pi_xi,
                                               chunk=blk))
        scores, hyp_out = eig_scores_refresh_pallas(
            rows, hyp, hyp_t, c, pi, pi_xi, block=blk, interpret=True)
        np.testing.assert_allclose(ref, np.asarray(scores),
                                   rtol=1e-4, atol=1e-6)
        assert int(ref.argmax()) == int(np.asarray(scores).argmax())
        np.testing.assert_array_equal(np.asarray(hyp_ref),
                                      np.asarray(hyp_out))


def test_refresh_preserves_untouched_rows():
    """The fused kernel writes ONLY the refreshed class row (the row-out
    BlockSpec is indexed by the scalar-prefetched class); every other row
    of the donated cache must carry over BITWISE — the property the
    row-only aliased write depends on, in interpret mode exactly as on
    hardware. Middle class, multiple N-blocks, ragged tail."""
    from coda_tpu.ops.pallas_eig import eig_scores_refresh_pallas

    N, C, H = 200, 7, 11
    rows, hyp, pi, pi_xi = _random_cache(jax.random.PRNGKey(9), N, C, H)
    hyp_t = jax.random.uniform(jax.random.PRNGKey(10), (N, H)) + 0.1
    hyp_t /= hyp_t.sum(-1, keepdims=True)
    c = 3
    _, hyp_out = eig_scores_refresh_pallas(
        rows, hyp, hyp_t, jnp.int32(c), pi, pi_xi, block=48, interpret=True)
    out = np.asarray(hyp_out)
    np.testing.assert_array_equal(out[c], np.asarray(hyp_t))
    untouched = [i for i in range(C) if i != c]
    np.testing.assert_array_equal(out[untouched], np.asarray(hyp)[untouched])


def test_fused_refresh_score_bf16_cache():
    """bf16 storage: the returned cache keeps the storage dtype and the
    refreshed row equals the bf16-rounded replacement values."""
    from coda_tpu.ops.pallas_eig import eig_scores_refresh_pallas

    rows, hyp, pi, pi_xi = _random_cache(jax.random.PRNGKey(4), 96, 3, 10)
    hyp16 = hyp.astype(jnp.bfloat16)
    hyp_t = jax.random.uniform(jax.random.PRNGKey(5), (96, 10)) + 0.1
    hyp_t /= hyp_t.sum(-1, keepdims=True)
    c = jnp.int32(1)
    scores, hyp_out = eig_scores_refresh_pallas(
        rows, hyp16, hyp_t, c, pi, pi_xi, block=32, interpret=True)
    assert hyp_out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(hyp_out[1]),
        np.asarray(hyp_t.astype(jnp.bfloat16)))
    # untouched rows carry over bitwise
    np.testing.assert_array_equal(np.asarray(hyp_out[0]),
                                  np.asarray(hyp16[0]))
    # SCORE parity with DUS-then-score: the kernel must score the
    # bf16-ROUNDED replacement row, not the raw fp32 values
    from coda_tpu.selectors.coda import eig_scores_from_cache

    ref = np.asarray(eig_scores_from_cache(
        rows, hyp16.at[1].set(hyp_t.astype(jnp.bfloat16)),
        pi, pi_xi, chunk=32))
    np.testing.assert_allclose(ref, np.asarray(scores),
                               rtol=1e-4, atol=1e-6)


def test_pallas_kernels_vmap_fallback():
    """vmapped pallas scorers dispatch to the EXPLICITLY batched kernels
    (batch = extra grid axis — pallas' automatic batching rule would pad
    the small tiles pathologically on TPU; observed scoped-VMEM OOM on
    the suite's width-1 seed probe, round 4) and must match the jnp path
    per element."""
    from coda_tpu.ops.pallas_eig import (
        eig_scores_cache_pallas,
        eig_scores_refresh_pallas,
    )
    from coda_tpu.selectors.coda import eig_scores_from_cache

    B = 3
    keys = jax.random.split(jax.random.PRNGKey(7), B)
    packs = [_random_cache(k, 64, 4, 10) for k in keys]
    rows = jnp.stack([p[0] for p in packs])
    hyp = jnp.stack([p[1] for p in packs])
    pi = jnp.stack([p[2] for p in packs])
    pi_xi = jnp.stack([p[3] for p in packs])

    out = jax.vmap(
        lambda r, h, p, px: eig_scores_cache_pallas(r, h, p, px, block=32)
    )(rows, hyp, pi, pi_xi)
    ref = jax.vmap(
        lambda r, h, p, px: eig_scores_from_cache(r, h, p, px, chunk=32)
    )(rows, hyp, pi, pi_xi)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-6)

    hyp_t = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(8), (B, 64, 10)), axis=-1)
    cs = jnp.arange(B, dtype=jnp.int32) % 4
    s_f, hyp_f = jax.vmap(
        lambda r, h, ht, c, p, px: eig_scores_refresh_pallas(
            r, h, ht, c, p, px, block=32)
    )(rows, hyp, hyp_t, cs, pi, pi_xi)
    for b in range(B):
        hyp2 = hyp[b].at[cs[b]].set(hyp_t[b])
        ref_b = eig_scores_from_cache(rows[b], hyp2, pi[b], pi_xi[b],
                                      chunk=32)
        np.testing.assert_allclose(np.asarray(ref_b), np.asarray(s_f[b]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(hyp2), np.asarray(hyp_f[b]))


def test_pallas_kernels_nested_vmap_flattens():
    """Task-over-seed nesting (the run_batched production shape) must
    flatten into the batched kernels' single grid axis and match the jnp
    composition per (task, seed)."""
    from coda_tpu.ops.pallas_eig import (
        eig_scores_cache_pallas,
        eig_scores_refresh_pallas,
    )
    from coda_tpu.selectors.coda import eig_scores_from_cache

    T, S, N, C, H = 2, 3, 40, 4, 10
    keys = jax.random.split(jax.random.PRNGKey(17), T * S)
    packs = [_random_cache(k, N, C, H) for k in keys]

    def stack(i):
        return jnp.stack([p[i] for p in packs]).reshape(
            (T, S) + packs[0][i].shape)

    rows, hyp, pi, pi_xi = stack(0), stack(1), stack(2), stack(3)

    score2 = jax.vmap(jax.vmap(
        lambda r, h, p, px: eig_scores_cache_pallas(r, h, p, px, block=16)))
    out = score2(rows, hyp, pi, pi_xi)
    ref = jax.vmap(jax.vmap(
        lambda r, h, p, px: eig_scores_from_cache(r, h, p, px, chunk=16)))(
        rows, hyp, pi, pi_xi)
    assert out.shape == (T, S, N)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-6)

    hyp_t = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(18), (T, S, N, H)), axis=-1)
    cs = (jnp.arange(T * S, dtype=jnp.int32) % C).reshape(T, S)
    fused2 = jax.vmap(jax.vmap(
        lambda r, h, ht, c, p, px: eig_scores_refresh_pallas(
            r, h, ht, c, p, px, block=16)))
    s_f, hyp_f = fused2(rows, hyp, hyp_t, cs, pi, pi_xi)
    assert s_f.shape == (T, S, N) and hyp_f.shape == (T, S, C, N, H)
    for t in range(T):
        for s in range(S):
            hyp2 = hyp[t, s].at[cs[t, s]].set(hyp_t[t, s])
            ref_b = eig_scores_from_cache(rows[t, s], hyp2, pi[t, s],
                                          pi_xi[t, s], chunk=16)
            np.testing.assert_allclose(
                np.asarray(ref_b), np.asarray(s_f[t, s]),
                rtol=1e-4, atol=1e-6, err_msg=f"({t},{s})")
            np.testing.assert_array_equal(np.asarray(hyp2),
                                          np.asarray(hyp_f[t, s]))


def test_fused_compute_refresh_matches_precomputed():
    """eig_refresh='fused' (the in-kernel row computation) must reproduce
    the precomputed path's scores and refreshed cache up to the
    documented opt-in tolerance (in-kernel fp32 dots vs XLA-HIGHEST
    einsums), and the full experiment trace must match the jnp path on
    tie-free synthetic data."""
    import jax.numpy as jnp

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine import run_experiment
    from coda_tpu.ops.pallas_eig import eig_scores_refresh_compute_pallas
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import (
        eig_scores_from_cache,
        update_eig_cache_parts,
    )
    from coda_tpu.ops.beta import dirichlet_to_beta
    from coda_tpu.ops.pbest import compute_pbest

    # kernel-level: random dirichlets -> tables -> fused row+score
    N, C, H = 77, 4, 10
    key = jax.random.PRNGKey(3)
    dir_ = jax.random.uniform(key, (H, C, C)) * 3.0 + 0.5
    hard = jax.random.randint(jax.random.PRNGKey(4), (N, H), 0, C
                              ).astype(jnp.int32)
    a_cc, b_cc = dirichlet_to_beta(dir_)
    c = jnp.int32(2)
    a_t, b_t = a_cc[:, c], b_cc[:, c]
    rows0 = compute_pbest(a_cc.T, b_cc.T)
    rows = rows0.at[c].set(compute_pbest(a_t, b_t))
    hyp = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(5), (C, N, H)), axis=-1)
    pi_xi = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(6), (N, C)), axis=-1)
    pi = pi_xi.mean(0) / pi_xi.mean(0).sum()

    row_t, hyp_t = update_eig_cache_parts(dir_, c, hard)
    hyp_ref = hyp.at[c].set(hyp_t)
    s_ref = eig_scores_from_cache(rows, hyp_ref, pi, pi_xi, chunk=32)
    s_fu, hyp_fu = eig_scores_refresh_compute_pallas(
        rows, hyp, a_t, b_t, hard, c, pi, pi_xi, block=32, interpret=True)
    np.testing.assert_allclose(np.asarray(hyp_ref), np.asarray(hyp_fu),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(s_ref), np.asarray(s_fu),
                               rtol=1e-3, atol=2e-5)

    # experiment-level: same selection trace as the jnp default
    task = make_synthetic_task(seed=4, H=6, N=64, C=4)
    res_j = run_experiment(
        make_coda(task.preds, CODAHyperparams(eig_mode="incremental")),
        task, iters=10, seed=0)
    res_f = run_experiment(
        make_coda(task.preds, CODAHyperparams(
            eig_mode="incremental", eig_backend="pallas",
            eig_refresh="fused")),
        task, iters=10, seed=0)
    np.testing.assert_array_equal(np.asarray(res_j.chosen_idx),
                                  np.asarray(res_f.chosen_idx))
    np.testing.assert_array_equal(np.asarray(res_j.best_model),
                                  np.asarray(res_f.best_model))


def test_fused_compute_refresh_guards():
    import pytest

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.selectors import CODAHyperparams, make_coda

    t = make_synthetic_task(seed=1, H=4, N=32, C=4)
    with pytest.raises(ValueError, match="unknown eig_refresh"):
        make_coda(t.preds, CODAHyperparams(eig_refresh="Fused"))
    # fused requires the pallas backend
    with pytest.raises(ValueError, match="pallas"):
        make_coda(t.preds, CODAHyperparams(eig_refresh="fused",
                                           eig_backend="jnp"))
    with pytest.raises(ValueError, match="vmapped"):
        make_coda(t.preds, CODAHyperparams(eig_refresh="fused",
                                           eig_backend="pallas",
                                           n_parallel=4))


def test_fused_compute_long_horizon_widepool_trace():
    """VERDICT r5 item 6: the fused-compute drift (measured 2.34e-4 on
    row values at the headline shape, PALLAS_TPU_VALIDATION_r05.json)
    must not accumulate into selection divergence over a LONG horizon.
    100 rounds of eig_refresh='fused' vs the default path on the WIDEST
    committed real pool (digits_h80: 80 sklearn models on real scans) —
    identical label-selection trace and best-model trace. The drift
    cannot compound structurally (each refresh recomputes its class row
    from the Dirichlet posterior, which both paths update identically —
    see the eig_refresh hyperparam docs); this pins it empirically."""
    import os

    import pytest as _pytest

    fp = os.path.join(os.path.dirname(__file__), "..", "data",
                      "digits_h80.npz")
    if not os.path.exists(fp):
        _pytest.skip("committed digits_h80 task not present")
    from coda_tpu.data import Dataset
    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda

    ds = Dataset.from_file(fp)
    r_def = run_experiment(
        make_coda(ds.preds, CODAHyperparams(eig_mode="incremental")),
        ds, iters=100, seed=0)
    r_fus = run_experiment(
        make_coda(ds.preds, CODAHyperparams(
            eig_mode="incremental", eig_backend="pallas",
            eig_refresh="fused")),
        ds, iters=100, seed=0)
    np.testing.assert_array_equal(np.asarray(r_def.chosen_idx),
                                  np.asarray(r_fus.chosen_idx))
    np.testing.assert_array_equal(np.asarray(r_def.best_model),
                                  np.asarray(r_fus.best_model))


def test_fused_compute_refresh_real_data_trace():
    """eig_refresh='fused' reproduces the default path's full selection
    trace on the committed REAL digits task (the strongest opt-in
    evidence available off-silicon: 30 rounds of real-model predictions,
    interpret-mode kernel)."""
    import os

    import pytest as _pytest

    fp = os.path.join(os.path.dirname(__file__), "..", "data", "digits.npz")
    if not os.path.exists(fp):
        _pytest.skip("committed digits task not present")
    from coda_tpu.data import Dataset
    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda

    ds = Dataset.from_file(fp)
    r_def = run_experiment(
        make_coda(ds.preds, CODAHyperparams(eig_mode="incremental")),
        ds, iters=30, seed=0)
    r_fus = run_experiment(
        make_coda(ds.preds, CODAHyperparams(
            eig_mode="incremental", eig_backend="pallas",
            eig_refresh="fused")),
        ds, iters=30, seed=0)
    np.testing.assert_array_equal(np.asarray(r_def.chosen_idx),
                                  np.asarray(r_fus.chosen_idx))
    np.testing.assert_array_equal(np.asarray(r_def.best_model),
                                  np.asarray(r_fus.best_model))
