"""Fault-tolerant serving tests (``coda_tpu/serve/recovery.py`` +
``coda_tpu/serve/faults.py``).

The load-bearing claims: (1) a session is fully determined by its
recorder JSONL stream — export/import and crash restore rebuild it
BITWISE on the same backend (pinned against uninterrupted control runs,
including across a real SIGKILL); (2) a bucket whose slab was lost to a
failed donated step heals by replaying its sessions' streams,
digest-verified, and an unverifiable rebuild degrades to terminal
instead of serving; (3) a client-supplied ``request_id`` makes label
submission idempotent — across retries, concurrency, and migration;
(4) every injection point in the fault matrix ends in a recovered
session or an attributable error (``scripts/check_fault_matrix.py``,
wired here at tier-1).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid

import numpy as np
import pytest

H, N, C = 4, 48, 4
_ROW_KEYS = ("next_idx", "next_prob", "best", "pbest_max", "pbest_entropy")


@pytest.fixture(scope="module")
def task():
    from coda_tpu.data import make_synthetic_task

    return make_synthetic_task(seed=0, H=H, N=N, C=C)


def _app(task, capacity=4, fault_spec=None, recorder=None, warm=False):
    from coda_tpu.serve import SelectorSpec, ServeApp

    app = ServeApp(capacity=capacity, max_wait=0.001,
                   spec=SelectorSpec.create("coda", n_parallel=capacity),
                   fault_spec=fault_spec, recorder=recorder)
    app.add_task(task.name, task.preds)
    app.start(warm=warm)
    return app


def _drive(app, seed, rounds):
    """Open + drive one session with the deterministic label policy
    (label = proposed idx mod C); returns its sid."""
    out = app.open_session(seed=seed)
    sid = out["session"]
    for _ in range(rounds):
        out = app.label(sid, int(out["idx"]) % C)
    return sid


def _last_row(app, sid):
    """The session's full last result row (the HTTP payload drops the
    posterior digest; the raw row keeps it)."""
    return {k: app.store.get(sid).last[k] for k in _ROW_KEYS}


def _assert_rows_bitwise(a, b, what=""):
    for k in _ROW_KEYS:
        va, vb = a[k], b[k]
        if isinstance(va, float):
            assert np.float32(va).tobytes() == np.float32(vb).tobytes(), \
                (what, k, va, vb)
        else:
            assert va == vb, (what, k, va, vb)


# ---------------------------------------------------------------------------
# export / import: checkpoint, migration, verification
# ---------------------------------------------------------------------------

def test_export_import_snapshot_path_bitwise(task):
    """Snapshot fast path: export a live session, import it on a second
    server (same backend + config -> fingerprint matches, digest
    verifies), continue it — the continued trajectory is BITWISE the
    uninterrupted control run, and the session keeps its id."""
    a, b = _app(task), _app(task)
    try:
        sid = _drive(a, seed=3, rounds=3)
        payload = a.export_session(sid)
        assert payload["v"] == 1
        assert payload["carries"] is not None    # slab was readable
        assert payload["n_labeled"] == 3
        assert a.metrics.snapshot()["recovery"]["exported"] == 1

        info = b.import_session(payload)
        assert info["restored_via"] == "snapshot"
        assert info["session"] == sid            # the handle survives
        assert b.store.get(sid).n_labeled == 3
        out = dict(b.store.get(sid).last)
        for _ in range(2):
            r = b.label(sid, int(out["next_idx"]) % C)
            out = b.store.get(sid).last
        assert r["n_labeled"] == 5

        control = _drive(a, seed=3, rounds=5)
        _assert_rows_bitwise(_last_row(b, sid), _last_row(a, control),
                             "snapshot-restored vs control")
    finally:
        a.drain(timeout=5)
        b.drain(timeout=5)


def test_export_import_replay_path_bitwise(task):
    """Replay path: the same payload stripped of its carries snapshot
    restores by re-driving the stream through the compiled step — every
    round verified — and lands on the identical state."""
    a, b = _app(task), _app(task)
    try:
        sid = _drive(a, seed=5, rounds=4)
        before = _last_row(a, sid)
        payload = a.export_session(sid)
        payload["carries"] = payload["key"] = None   # force the slow path

        info = b.import_session(payload)
        assert info["restored_via"] == "replay"
        assert b.store.get(sid).n_labeled == 4
        _assert_rows_bitwise(_last_row(b, sid), before,
                             "replay-restored vs exporter")
        # the restored slot's standalone posterior digest equals the
        # stream's last recorded digest (the heal/import verification)
        bucket = b.store.get(sid).bucket
        with bucket.lock:
            got = bucket.digest(b.store.get(sid).slot)
        assert np.float32(got[0]).tobytes() == \
            np.float32(before["pbest_max"]).tobytes()
    finally:
        a.drain(timeout=5)
        b.drain(timeout=5)


def test_import_rejects_tamper_and_mismatch(task):
    """A payload whose stream cannot be verified — tampered label, forged
    digest, wrong dataset, wrong version — is rejected whole, never
    half-admitted (no session leaks)."""
    from coda_tpu.serve import ImportRejected

    a, b = _app(task), _app(task)
    try:
        sid = _drive(a, seed=7, rounds=3)
        clean = a.export_session(sid)

        def stripped(**edits):
            p = json.loads(json.dumps(clean))    # deep copy
            p["carries"] = p["key"] = None       # force replay verification
            p.update(edits)
            return p

        # tampered oracle answer: replay diverges at the exact round
        p = stripped()
        p["rows"][1]["label"] = (int(p["rows"][1]["label"]) + 1) % C
        with pytest.raises(ImportRejected, match="replay verification"):
            b.import_session(p)
        # forged posterior digest: the bitwise check catches one flipped
        # float even though idx/best still agree
        p = stripped()
        p["rows"][2]["pbest_max"] = float(p["rows"][2]["pbest_max"]) + 1e-4
        with pytest.raises(ImportRejected, match="replay verification"):
            b.import_session(p)
        # different data answers a different question
        p = stripped()
        p["dataset"]["digest"] = "0" * 64
        with pytest.raises(ImportRejected, match="dataset digest"):
            b.import_session(p)
        # versioned payloads: an unknown version is refused outright
        p = stripped(v=999)
        with pytest.raises(ImportRejected, match="v=999"):
            b.import_session(p)
        # nothing half-admitted: every rejected sid was closed again
        assert not b.store.alive(sid)
        assert b.metrics.snapshot()["recovery"]["imported"] == 0
    finally:
        a.drain(timeout=5)
        b.drain(timeout=5)


def test_import_rejects_invalid_session_id(task, tmp_path):
    """A client-supplied session id is an HTTP handle AND a recorder file
    path component: anything but the lowercase hex this package mints is
    refused before it can touch the store or the filesystem."""
    from coda_tpu.serve import ImportRejected
    from coda_tpu.telemetry import SessionRecorder

    a = _app(task)
    b = _app(task, recorder=SessionRecorder(out_dir=str(tmp_path)))
    try:
        sid = _drive(a, seed=2, rounds=2)
        clean = a.export_session(sid)
        for bad in ("../../../tmp/evil", "ABCDEF", "a" * 65, "", 7, None):
            p = json.loads(json.dumps(clean))
            p["session"] = bad
            with pytest.raises(ImportRejected, match="session id"):
                b.import_session(p)
        assert list(tmp_path.iterdir()) == []    # nothing escaped or leaked
        assert not b.store._sessions             # no unreachable session
    finally:
        a.drain(timeout=5)
        b.drain(timeout=5)


def test_import_history_reconciles_migrate_back_stream(tmp_path):
    """A session that migrated away from this record dir (close marker)
    and comes back with rounds accrued elsewhere must get its file
    REWRITTEN as the full imported history — resuming append-only would
    leave a row gap that a later crash restore replays into a false
    divergence. A live prefix (crash restore against the same dir) still
    resumes without duplicating rows."""
    from coda_tpu.serve.recovery import load_session_stream
    from coda_tpu.telemetry import SessionRecorder

    def mkrows(n):
        return [{"n_labeled": i + 1, "do_update": True, "labeled_idx": i,
                 "label": 0, "prob": 0.5, "next_idx": i + 1,
                 "next_prob": 0.5, "best": 0, "stochastic": False}
                for i in range(n)]

    path = os.path.join(str(tmp_path), "session_abc.jsonl")
    rec = SessionRecorder(out_dir=str(tmp_path))
    rec.open("abc", meta={"task": "t"})
    for r in mkrows(5):
        rec.append("abc", r)
    rec.close("abc")                     # migrated away: close marker
    rec2 = SessionRecorder(out_dir=str(tmp_path))
    rec2.import_history("abc", meta={"task": "t"}, rows=mkrows(10))
    meta, rows, closed = load_session_stream(path)
    assert not closed and meta.get("task") == "t"
    assert [r["n_labeled"] for r in rows] == list(range(1, 11))
    # live appends continue cleanly after the rewrite
    rec2.append("abc", mkrows(11)[-1])
    _, rows, _ = load_session_stream(path)
    assert len(rows) == 11

    # crash-restore shape: an UN-closed prefix resumes append-only
    rec3 = SessionRecorder(out_dir=str(tmp_path))
    rec3.open("def", meta={"task": "t"})
    for r in mkrows(5):
        rec3.append("def", r)           # crash: no close marker
    p2 = os.path.join(str(tmp_path), "session_def.jsonl")
    rec4 = SessionRecorder(out_dir=str(tmp_path))
    rec4.import_history("def", meta={"task": "t"}, rows=mkrows(5))
    _, rows, _ = load_session_stream(p2)
    assert [r["n_labeled"] for r in rows] == list(range(1, 6))  # no dupes
    rec5 = SessionRecorder(out_dir=str(tmp_path))
    rec5.import_history("def", meta={"task": "t"}, rows=mkrows(7))
    _, rows, _ = load_session_stream(p2)
    assert [r["n_labeled"] for r in rows] == list(range(1, 8))  # suffix only


def test_concurrent_export_during_dispatch_regression(task):
    """The export/donation race: exporting a slot while donated slab
    steps are consuming the bucket's carries. ``snapshot_slot`` must
    host-materialize under the dispatch lock, so every export payload
    carries a usable snapshot (never 'Array has been deleted', never a
    torn state) and every payload imports cleanly — snapshot when the
    digest still matches, verified replay when a dispatch raced ahead."""
    a, b = _app(task, capacity=4), _app(task, capacity=4)
    try:
        target = _drive(a, seed=0, rounds=1)
        others = [_drive(a, seed=s, rounds=1) for s in (1, 2)]
        stop = threading.Event()
        errors: list = []

        def hammer():
            # keep donated slab steps flowing on ALL slots — including
            # the exported one — until the exports are done
            try:
                while not stop.is_set():
                    for sid in (target, *others):
                        out = a.store.get(sid).last
                        a.label(sid, int(out["next_idx"]) % C)
            except Exception as e:
                errors.append(e)

        th = threading.Thread(target=hammer, daemon=True)
        th.start()
        payloads = []
        try:
            for _ in range(12):
                payloads.append(a.export_session(target))
        finally:
            stop.set()
            th.join(timeout=30)
        assert not errors, errors
        for i, p in enumerate(payloads):
            # the satellite's pin: the snapshot was taken BEFORE any next
            # donated step could consume the carries — so it exists...
            assert p["carries"] is not None, f"export {i} lost the race"
            # ...and the payload restores: bitwise-verified either way
            info = b.import_session(p)
            assert info["restored_via"] in ("snapshot", "replay")
            assert b.store.get(target).n_labeled == p["n_labeled"]
            b.close_session(target)
    finally:
        a.drain(timeout=5)
        b.drain(timeout=5)


# ---------------------------------------------------------------------------
# idempotent labels (request_id dedupe)
# ---------------------------------------------------------------------------

def test_label_request_id_applies_exactly_once(task):
    a = _app(task)
    try:
        out = a.open_session(seed=0)
        sid = out["session"]
        rid = uuid.uuid4().hex
        first = a.label(sid, int(out["idx"]) % C, request_id=rid)
        assert first["n_labeled"] == 1
        # a retried submission is answered from the committed result
        replay = a.label(sid, int(out["idx"]) % C, request_id=rid)
        assert a.store.get(sid).n_labeled == 1
        for k in ("idx", "prob", "best"):
            assert replay[k] == first[k], k
        # a NEW request_id is a new logical label
        a.label(sid, int(first["idx"]) % C, request_id=uuid.uuid4().hex)
        assert a.store.get(sid).n_labeled == 2
    finally:
        a.drain(timeout=5)


def test_label_request_id_concurrent_retries(task):
    """Eight concurrent retries of the same logical label: the posterior
    applies it once; every caller gets the same answer."""
    a = _app(task)
    try:
        out = a.open_session(seed=1)
        sid, rid = out["session"], uuid.uuid4().hex
        lab = int(out["idx"]) % C
        results, errors = [], []

        def submit():
            try:
                results.append(a.label(sid, lab, request_id=rid))
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=submit) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert a.store.get(sid).n_labeled == 1
        assert len({(r["idx"], r["prob"], r["best"]) for r in results}) == 1
    finally:
        a.drain(timeout=5)


def test_label_cancel_racing_inflight_dispatch_no_double_apply(task):
    """The narrowest double-apply window: a label ticket's client-side
    cancel (wait timeout) lands while its dispatch is ALREADY in flight,
    and the client's retry re-registers the same request_id before the
    dispatch commits. The in-flight dispatch still applies + commits its
    result (cancel lost the resolution race, by design) — the retry
    ticket must then be answered from that committed result, never
    dispatched: dispatching it would apply the oracle answer twice."""
    # slow_step fires on the label dispatch (arrival 0 is the open),
    # holding the step inside the lock long enough to land the cancel
    # and the retry deterministically mid-dispatch
    a = _app(task, fault_spec="slow_step:after=1,ms=400")
    try:
        out = a.open_session(seed=7)
        sid, rid = out["session"], uuid.uuid4().hex
        lab = int(out["idx"]) % C
        sess, t1 = a._label_begin(sid, lab, None, rid)
        deadline = time.perf_counter() + 5
        while t1.collected == 0 and time.perf_counter() < deadline:
            time.sleep(0.005)
        assert t1.collected != 0, "label ticket never collected"
        time.sleep(0.05)           # inside the slow_step window
        assert t1.cancel("client wait timed out"), \
            "dispatch commit beat the test's cancel; race not exercised"
        # the client retries the same logical label while t1's dispatch
        # is still in flight: pending[rid] is dead -> a NEW ticket submits
        _, t2 = a._label_begin(sid, lab, None, rid)
        assert t2 is not t1
        res = t2.wait(10)
        assert a.store.get(sid).n_labeled == 1      # applied exactly once
        rows = [r for r in a.recorder.history(sid)
                if r.get("do_update") and r.get("request_id") == rid]
        assert len(rows) == 1                       # one recorded apply
        # and the retry read the committed result, not a re-dispatch
        assert res["next_idx"] == rows[0]["next_idx"]
        assert rid not in sess.pending              # registration settled
    finally:
        a.drain(timeout=5)


def test_label_dedupe_survives_migration(task):
    """A label applied on the old server then retried (same request_id)
    against the new one after import must dedupe there too — the cache is
    repopulated from the stream's recorded request_ids."""
    a, b = _app(task), _app(task)
    try:
        out = a.open_session(seed=2)
        sid, rid = out["session"], uuid.uuid4().hex
        applied = a.label(sid, int(out["idx"]) % C, request_id=rid)
        b.import_session(a.export_session(sid))
        retried = b.label(sid, int(out["idx"]) % C, request_id=rid)
        assert b.store.get(sid).n_labeled == 1       # not double-applied
        for k in ("idx", "best"):
            assert retried[k] == applied[k], k
    finally:
        a.drain(timeout=5)
        b.drain(timeout=5)


# ---------------------------------------------------------------------------
# bucket self-healing
# ---------------------------------------------------------------------------

def test_heal_rebuilds_quarantined_slab_bitwise(task):
    """A quarantined bucket (slab lost) heals by replaying every live
    session's stream into a fresh slab — after the heal, continued
    trajectories are bitwise the uninterrupted control run."""
    a = _app(task, capacity=6)
    try:
        sids = [_drive(a, seed=s, rounds=3) for s in (0, 1)]
        bucket = a.store.buckets()[0]
        bucket.quarantined = "test: simulated donated-step failure"
        assert a.healer.schedule(bucket, sync=True)
        assert bucket.quarantined is None and bucket.failed is None
        assert bucket.heals == 1
        assert a.metrics.snapshot()["recovery"]["healed"] == 1
        # healed sessions keep serving, on the control trajectory
        for seed, sid in enumerate(sids):
            out = a.store.get(sid).last
            a.label(sid, int(out["next_idx"]) % C)
            control = _drive(a, seed=seed, rounds=4)
            _assert_rows_bitwise(_last_row(a, sid), _last_row(a, control),
                                 f"healed seed {seed} vs control")
    finally:
        a.drain(timeout=5)


def test_quarantined_bucket_fails_fast_without_lock(task):
    """While the healer holds the bucket lock for the whole slab rebuild,
    a label dispatch must fail fast (retryable BucketQuarantined) instead
    of blocking the single batcher thread on that lock — which would
    stall every OTHER bucket's dispatches behind one bucket's recovery."""
    from coda_tpu.serve.state import BucketQuarantined

    a = _app(task)
    try:
        sid = _drive(a, seed=0, rounds=1)
        bucket = a.store.get(sid).bucket
        with bucket.lock:                # the healer mid-rebuild
            bucket.quarantined = "test: slab rebuild in progress"
            t0 = time.perf_counter()
            _, ticket = a._label_begin(sid, 0, None, None)
            with pytest.raises(BucketQuarantined):
                ticket.wait(20)
            assert time.perf_counter() - t0 < 10   # not lock-blocked
            bucket.quarantined = None
        # quarantine lifted: the retry lands
        out = a.store.get(sid).last
        a.label(sid, int(out["next_idx"]) % C)
        assert a.store.get(sid).n_labeled == 2
    finally:
        a.drain(timeout=5)


def test_submit_racing_stop_never_strands_ticket():
    """The submit/stop TOCTOU: a submit that passes the running check
    while a concurrent stop() completes (final queue flush included)
    before the put lands must still resolve the ticket with the retryable
    drain error — not strand it until the 60 s request timeout."""
    import queue as _queue

    from coda_tpu.serve.batcher import Batcher, Ticket

    b = Batcher(store=None)
    b.start()

    class RacingQueue(_queue.Queue):
        def put(self, item, *args, **kwargs):
            if b._thread is not None:
                b.stop(drain=False, timeout=5)   # stop wins the race
            super().put(item, *args, **kwargs)

    b.queue = RacingQueue()                      # nothing queued yet
    t = b.submit(Ticket(session=None, do_update=False))
    assert t.done.is_set(), "ticket stranded by the stop/submit race"
    with pytest.raises(RuntimeError, match="draining"):
        t.wait(1)


def test_restoring_session_gates_labels_retryably(task):
    """While import/restore is mid-replay the sid is already addressable
    (the client's handle must resolve) but the posterior and dedupe cache
    are not rebuilt — a label landing in that window must get a retryable
    503-class error, never a 404 or a double-apply."""
    from coda_tpu.serve.state import BucketQuarantined

    a = _app(task)
    try:
        sid = _drive(a, seed=0, rounds=1)
        sess = a.store.get(sid)
        sess.restoring = True
        with pytest.raises(BucketQuarantined, match="being restored"):
            a.label(sid, 0)
        with pytest.raises(BucketQuarantined, match="being restored"):
            a.close_session(sid)   # freeing the slot mid-replay would let
        with pytest.raises(BucketQuarantined, match="being restored"):
            a.export_session(sid)  # ...and an export would serialize an
        with pytest.raises(BucketQuarantined, match="being restored"):
            a.best(sid)            # the slot holds a partially-replayed
        with pytest.raises(BucketQuarantined, match="being restored"):
            a.trace(sid)           # posterior and a half-built history
        sess.restoring = False     # empty stream as the session
        a.label(sid, int(sess.last["next_idx"]) % C)
        assert sess.n_labeled == 2
    finally:
        a.drain(timeout=5)


def test_max_heals_degradation_counts_as_heal_failure(task):
    """The max_heals cap is a terminal degradation like any other: it
    must ride the heal-failure metrics, not silently flatline them."""
    from coda_tpu.serve.recovery import BucketHealer

    a = _app(task)
    try:
        _drive(a, seed=0, rounds=1)
        bucket = a.store.buckets()[0]
        healer = BucketHealer(a.store, a.recorder, metrics=a.metrics,
                              max_heals=0)
        bucket.quarantined = "test: persistent step failure"
        assert healer.schedule(bucket) is False
        assert bucket.failed is not None and "exceeded 0" in bucket.failed
        assert a.metrics.snapshot()["recovery"]["heal_failed"] == 1
    finally:
        bucket.failed = None   # let drain shut down cleanly
        a.drain(timeout=5)


def test_import_path_quarantine_schedules_heal(task):
    """A quarantine raised on the import/restore path (which never rides
    a batcher tick, so the batcher's failure hook can't see it) must
    still get a heal scheduled — not leave the bucket 503-refused until
    the next label happens to arrive."""
    a = _app(task)
    try:
        sid = _drive(a, seed=0, rounds=2)
        payload = a.export_session(sid, close=True)
        bucket = a.store.buckets()[0]
        bucket.quarantined = "test: replay dispatch consumed carries"
        with pytest.raises(Exception):
            a.import_session(payload)   # allocate -> BucketQuarantined
        deadline = time.perf_counter() + 10
        while bucket.quarantined is not None and \
                time.perf_counter() < deadline:
            time.sleep(0.01)
        assert bucket.quarantined is None and bucket.failed is None
        assert bucket.heals == 1
        # the retried import now lands
        info = a.import_session(payload)
        assert info["session"] == sid
    finally:
        a.drain(timeout=5)


def test_slow_step_sleeps_only_fired_instances():
    """Two slow_step faults in one spec: a tick where only the first
    fires must sleep that instance's ms, not the sum of every configured
    slow_step (the name-match bug charged all of them)."""
    from coda_tpu.serve.faults import FaultInjector

    inj = FaultInjector("slow_step:every=1,ms=1;slow_step:after=100,ms=500")
    t0 = time.perf_counter()
    for _ in range(3):
        assert inj.fire("step_pre") == ["slow_step"]
    assert time.perf_counter() - t0 < 0.3    # 3x1ms, not 3x501ms
    snap = {(f["name"], f["fired"]) for f in inj.snapshot()}
    assert snap == {("slow_step", 3), ("slow_step", 0)}


def test_heal_digest_mismatch_degrades_terminal(task):
    """An unverifiable rebuild must never re-admit: a stream whose
    recorded digest cannot be reproduced leaves the bucket terminally
    failed (attributable), not silently serving a wrong posterior."""
    a = _app(task)
    try:
        sid = _drive(a, seed=0, rounds=2)
        # poison the RECORDED digest so the (correct) rebuild mismatches
        a.recorder.history(sid)[-1]["pbest_max"] += 1e-3
        bucket = a.store.buckets()[0]
        bucket.quarantined = "test: simulated donated-step failure"
        assert a.healer.schedule(bucket, sync=True)
        assert bucket.failed is not None
        assert "digest" in bucket.failed
        assert bucket.quarantined is None
        assert a.metrics.snapshot()["recovery"]["heal_failed"] == 1
        with pytest.raises(RuntimeError, match="failed"):
            a.label(sid, 0)
    finally:
        a.drain(timeout=5)


def test_quarantined_bucket_answers_retryable(task):
    """While a heal is pending, admissions and dispatches get the
    retryable BucketQuarantined — not a terminal error, not a hang."""
    from coda_tpu.serve import BucketQuarantined

    a = _app(task)
    try:
        sid = _drive(a, seed=0, rounds=1)
        bucket = a.store.buckets()[0]
        bucket.quarantined = "test: rebuild in progress"
        with pytest.raises(BucketQuarantined):
            bucket.allocate(seed=9)
        with pytest.raises(BucketQuarantined):
            a.label(sid, 0)
        assert "buckets_quarantined" in a.healthz()["problems"]
        bucket.quarantined = None
        a.label(sid, int(a.store.get(sid).last["next_idx"]) % C)  # recovers
    finally:
        a.drain(timeout=5)


# ---------------------------------------------------------------------------
# rolling restart: the drain -> export -> restart -> import demo
# ---------------------------------------------------------------------------

def test_rolling_restart_zero_drop_zero_double(task):
    """The acceptance demo at test scale: retrying clients run through a
    live drain -> export -> import onto a fresh server. Zero dropped
    sessions, zero double-applied labels (every session lands on exactly
    its label budget), and every migrated session's stream on the NEW
    server replay-verifies bitwise against a fresh slab."""
    from scripts.serve_loadgen import with_retries

    from coda_tpu.serve import SessionStore
    from coda_tpu.serve.recovery import verify_session_stream

    a = _app(task, capacity=6)
    cur = {"app": a}
    rounds, n_sessions = 6, 4
    sids = [cur["app"].open_session(seed=s)["session"]
            for s in range(n_sessions)]
    errors: list = []
    retried: list = []

    def client(i):
        try:
            sid = sids[i]
            out = cur["app"].store.get(sid).last
            for _ in range(rounds):
                lab = int(out["next_idx"]) % C
                rid = uuid.uuid4().hex     # stable across this label's tries
                with_retries(
                    lambda: cur["app"].label(sid, lab, request_id=rid),
                    retries=10, backoff_s=0.05, counter=retried)
                out = cur["app"].store.get(sid).last
                time.sleep(0.01)           # keep the drain window populated
        except Exception as e:
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_sessions)]
    for t in threads:
        t.start()
    time.sleep(0.08)                       # let traffic flow, then migrate
    b = _app(task, capacity=6)
    try:
        a.quiesce(timeout=5)               # stop ticking, keep sessions
        for sid in sids:
            b.import_session(a.export_session(sid))
        cur["app"] = b                     # the "DNS flip"
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        for s, sid in enumerate(sids):
            n = b.store.get(sid).n_labeled
            assert n == rounds, (
                f"session {sid} (seed {s}): {n} labels applied, client "
                f"issued {rounds} — dropped or double-applied")
        # replay-verify every migrated stream against a fresh slab
        store = SessionStore(capacity=2)
        store.register_task(task.name, task.preds)
        for s, sid in enumerate(sids):
            meta = {"task": task.name, "method": b.spec.method,
                    "spec_kwargs": [list(kv) for kv in b.spec.kwargs],
                    "seed": s}
            info = verify_session_stream(store, meta,
                                         b.recorder.history(sid), sid=sid)
            assert info["parity"] and info["rounds"] == rounds + 1
    finally:
        a.drain(timeout=5)
        b.drain(timeout=5)


# ---------------------------------------------------------------------------
# crash recovery: SIGKILL mid-load, restore, bitwise vs control
# ---------------------------------------------------------------------------

_CRASH_COMMON = r"""
import sys, threading, time
from coda_tpu.data import make_synthetic_task
from coda_tpu.serve import ServeApp, SelectorSpec
from coda_tpu.telemetry import SessionRecorder
H, N, C = 4, 48, 4
d, R = sys.argv[1], int(sys.argv[2])
task = make_synthetic_task(seed=0, H=H, N=N, C=C)
app = ServeApp(capacity=4, max_wait=0.001,
               spec=SelectorSpec.create("coda", n_parallel=4),
               recorder=SessionRecorder(out_dir=d))
app.add_task(task.name, task.preds)
app.start(warm=False)
"""

_CRASH_SERVE = _CRASH_COMMON + r"""
outs = [app.open_session(seed=s) for s in range(3)]
def drive(out):
    sid = out["session"]
    for _ in range(R):
        out = app.label(sid, int(out["idx"]) % C)
        time.sleep(0.02)
threads = [threading.Thread(target=drive, args=(o,), daemon=True)
           for o in outs]
for t in threads:
    t.start()
print("SERVING", flush=True)
for t in threads:
    t.join()
print("DONE", flush=True)   # only if the parent's SIGKILL came too late
time.sleep(600)
"""

_CRASH_RESTORE = _CRASH_COMMON + r"""
import json
report = app.restore_sessions(d)
assert not report["failed"], f"restore failures: {report['failed']}"
assert len(report["restored"]) == 3, report
by_seed = {}
for sid in report["restored"]:
    sess = app.store.get(sid)
    out = dict(sess.last)
    while sess.n_labeled < R:   # finish the interrupted budget
        app.label(sid, int(out["next_idx"]) % C)
        out = dict(sess.last)
    by_seed[sess.seed] = {k: out[k] for k in
                          ("next_idx", "next_prob", "best",
                           "pbest_max", "pbest_entropy")}
app.drain(timeout=10)
print("RESULT " + json.dumps(by_seed), flush=True)
"""


def test_sigkill_crash_restore_bitwise_vs_control(task, tmp_path):
    """SIGKILL a serving process mid-load, restart against the same
    --record-dir, restore every session from its JSONL stream, finish
    each session's label budget — the final P(best) digests and
    best-model answers are BITWISE an uninterrupted control run's."""
    d, rounds = str(tmp_path / "rec"), 10
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    # phase 1: serve under load, then die by SIGKILL mid-load
    p = subprocess.Popen([sys.executable, "-c", _CRASH_SERVE, d,
                          str(rounds)],
                         env=env, cwd=repo, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True)
    try:
        line = ""
        deadline = time.time() + 300
        while "SERVING" not in line:
            line = p.stdout.readline()
            assert line, "serve child exited before taking load"
            assert time.time() < deadline, "serve child never came up"
        time.sleep(0.15)                   # mid-load: labels in flight
    finally:
        p.kill()                           # SIGKILL — no cleanup at all
    p.wait(timeout=60)
    assert p.returncode == -signal.SIGKILL
    streams = [f for f in os.listdir(d) if f.startswith("session_")]
    assert len(streams) == 3

    # phase 2: a fresh process restores from the streams and finishes
    out = subprocess.run([sys.executable, "-c", _CRASH_RESTORE, d,
                          str(rounds)],
                         env=env, cwd=repo, capture_output=True, text=True,
                         timeout=540)
    assert out.returncode == 0, out.stderr[-2000:]
    restored = json.loads(
        [ln for ln in out.stdout.splitlines()
         if ln.startswith("RESULT ")][-1][len("RESULT "):])
    assert sorted(restored) == ["0", "1", "2"]

    # control: the same sessions driven uninterrupted, in this process
    a = _app(task)
    try:
        for seed in range(3):
            sid = _drive(a, seed=seed, rounds=rounds)
            rec = restored[str(seed)]
            assert rec == {k: a.store.get(sid).last[k]
                           for k in rec}, f"seed {seed} diverged"
    finally:
        a.drain(timeout=5)


def test_heal_survives_session_closed_before_rebuild(task):
    """A session that closed between the failure and the heal needs no
    rebuild: the heal skips it instead of replaying into a freed slot,
    mismatching, and terminally failing the WHOLE bucket (which would
    kill every other healable session)."""
    a = _app(task, capacity=6)
    try:
        keep = _drive(a, seed=0, rounds=3)
        gone = _drive(a, seed=1, rounds=2)
        bucket = a.store.buckets()[0]
        bucket.quarantined = "test: simulated donated-step failure"
        a.close_session(gone)          # client bails during the outage
        assert a.healer.schedule(bucket, sync=True)
        assert bucket.failed is None and bucket.quarantined is None
        assert bucket.heals == 1
        out = a.store.get(keep).last
        a.label(keep, int(out["next_idx"]) % C)
        control = _drive(a, seed=0, rounds=4)
        _assert_rows_bitwise(_last_row(a, keep), _last_row(a, control),
                             "survivor vs control")
    finally:
        a.drain(timeout=5)


def test_restore_resumes_past_torn_tail(task, tmp_path):
    """A crash mid-write leaves a torn final line; resuming the stream
    must truncate it before appending — otherwise the next row glues onto
    the fragment and corrupts a MID-file line, making the stream
    permanently unrestorable."""
    from coda_tpu.serve.recovery import load_session_stream
    from coda_tpu.telemetry import SessionRecorder

    d = str(tmp_path)
    a = _app(task, recorder=SessionRecorder(out_dir=d))
    sid = _drive(a, seed=0, rounds=2)
    # abandon `a` un-drained (the crash) and tear the stream's tail
    path = os.path.join(d, f"session_{sid}.jsonl")
    with open(path, "ab") as f:
        f.write(b'{"v": 2, "n_labeled": 3, "do_update": true, "torn')
    b = _app(task, recorder=SessionRecorder(out_dir=d))
    try:
        report = b.restore_sessions(d)
        assert report["restored"] == [sid], report
        assert b.store.get(sid).n_labeled == 2   # torn row dropped
        out = b.store.get(sid).last
        b.label(sid, int(out["next_idx"]) % C)   # appends to the stream
        # every line in the resumed file parses; a THIRD restore works
        meta, rows, closed = load_session_stream(path)
        assert len(rows) == 4 and not closed
        c = _app(task, recorder=SessionRecorder(out_dir=str(tmp_path)))
        b.store.close(sid)  # free the sid so c can re-admit it
        report = c.restore_sessions(d)
        assert report["restored"] == [sid], report
        assert c.store.get(sid).n_labeled == 3
        c.drain(timeout=5)
    finally:
        b.drain(timeout=5)
        a.drain(timeout=5)


def test_old_schema_stream_rejected_with_real_reason(task, tmp_path):
    """A pre-upgrade (v1) stream lacks the per-round digest fields; it
    must be refused with a version message, not misreported as a bitwise
    divergence of data that was never recorded."""
    from coda_tpu.serve import SessionStore
    from coda_tpu.serve.recovery import verify_session_stream

    d = str(tmp_path)
    with open(os.path.join(d, "session_aa11.jsonl"), "w") as f:
        f.write(json.dumps({"v": 1, "kind": "session_meta",
                            "session": "aa11", "task": task.name,
                            "method": "coda", "seed": 0}) + "\n")
        f.write(json.dumps({"v": 1, "n_labeled": 0, "do_update": False,
                            "labeled_idx": None, "label": None,
                            "prob": None, "next_idx": 3, "next_prob": 0.5,
                            "best": 1, "stochastic": False}) + "\n")
    a = _app(task)
    try:
        report = a.restore_sessions(d)
        assert list(report["failed"]) == ["aa11"]
        assert "schema v1" in report["failed"]["aa11"]
    finally:
        a.drain(timeout=5)
    store = SessionStore(capacity=2)
    store.register_task(task.name, task.preds)
    with pytest.raises(ValueError, match="schema v1"):
        verify_session_stream(store, {"v": 1, "task": task.name}, [])


# ---------------------------------------------------------------------------
# recorder degradation + warm-up failure telemetry
# ---------------------------------------------------------------------------

def test_recorder_eio_degrades_stream_not_session(tmp_path):
    """A failed stream write (disk full) degrades THAT stream to
    memory-only; the in-memory history stays authoritative and the
    degradation is counted."""
    from coda_tpu.serve.faults import FaultInjector
    from coda_tpu.telemetry import SessionRecorder

    rec = SessionRecorder(out_dir=str(tmp_path),
                          faults=FaultInjector("record_eio:after=1"))
    rec.open("abc", meta={"task": "t"})            # arrival 1: writes
    rec.append("abc", {"next_idx": 1})             # arrival 2: EIO fires
    assert rec.degraded_streams == 1
    rec.append("abc", {"next_idx": 2})             # keeps serving
    assert [r["next_idx"] for r in rec.history("abc")] == [1, 2]
    # the on-disk stream kept only the pre-fault prefix; no torn rows
    with open(tmp_path / "session_abc.jsonl") as f:
        kinds = [json.loads(ln).get("kind") for ln in f if ln.strip()]
    assert kinds == ["session_meta"]
    rec.close("abc")                               # no crash on closed file


def test_warmup_failure_routed_through_telemetry(task, monkeypatch):
    """Satellite: a warm-pool failure is a counter + gauge + /stats field
    + degraded /healthz — and the server still starts (lazy fallback),
    instead of a bare print or a crash."""
    from coda_tpu.serve.state import Bucket
    from coda_tpu.telemetry import get_registry

    def boom(self):
        raise RuntimeError("injected warm-up failure")

    monkeypatch.setattr(Bucket, "warm", boom)
    before = get_registry().counter("serve_warmup_failures_total").value()
    a = _app(task, warm=True)          # sync warm path must degrade
    try:
        assert a.ready.is_set()
        assert "injected warm-up failure" in a.warm_error
        hz = a.healthz()
        assert hz["status"] == "degraded"
        assert "warmup_failed" in hz["problems"]
        assert "buckets_lazy" in hz["problems"]
        assert get_registry().counter(
            "serve_warmup_failures_total").value() == before + 1
        assert a.stats()["warm_error"] == a.warm_error
        assert a.stats()["status"] == "degraded"
    finally:
        a.drain(timeout=5)


def test_healthz_three_states(task):
    """unready (warming) / ok / degraded are distinct and attributable."""
    from coda_tpu.serve import SelectorSpec, ServeApp

    a = ServeApp(capacity=2, max_wait=0.001,
                 spec=SelectorSpec.create("coda", n_parallel=2))
    a.add_task(task.name, task.preds)
    assert a.healthz()["status"] == "unready"      # never started
    a.start(warm=False)
    try:
        assert a.healthz()["status"] == "ok"
        a.recorder.degraded_streams = 1
        hz = a.healthz()
        assert hz["status"] == "degraded"
        assert hz["problems"] == ["recorder_degraded"]
        assert hz["ok"] is True                    # live, just degraded
        a.recorder.degraded_streams = 0
        assert a.healthz()["status"] == "ok"
    finally:
        a.drain(timeout=5)


# ---------------------------------------------------------------------------
# fault-injection harness semantics
# ---------------------------------------------------------------------------

def test_fault_spec_grammar_and_determinism():
    from coda_tpu.serve.faults import (
        FaultInjected,
        FaultInjector,
        parse_fault_spec,
    )

    with pytest.raises(ValueError, match="unknown fault"):
        parse_fault_spec("explode:after=1")
    with pytest.raises(ValueError, match="unknown fault param"):
        parse_fault_spec("step_raise:when=later")
    assert parse_fault_spec(None) == [] and parse_fault_spec("") == []

    # after=N fires exactly once, on the (N+1)-th arrival
    inj = FaultInjector("step_raise:after=2")
    assert inj.fire("step_post") == []
    assert inj.fire("step_post") == []
    with pytest.raises(FaultInjected):
        inj.fire("step_post")
    assert inj.fire("step_post") == []             # budget spent
    assert inj.snapshot()[0]["fired"] == 1

    # every=N with a times budget; wrong site / wrong task never fires
    inj = FaultInjector("step_nan:every=2,times=2,task=a")
    assert inj.fire("step_out", task="b") == []
    hits = [bool(inj.fire("step_out", task="a")) for _ in range(8)]
    assert sum(hits) == 2 and hits[1] and hits[3]

    # p-draws are counter-addressed: two injectors with the same spec
    # fire on exactly the same arrivals ("seed-addressable")
    mk = lambda: FaultInjector("slow_step:p=0.3,seed=7,times=1000,ms=0")
    x, y = mk(), mk()
    seq = lambda inj: [bool(inj.fire("step_pre")) for _ in range(64)]
    sx = seq(x)
    assert sx == seq(y)
    assert 0 < sum(sx) < 64                         # actually probabilistic


def test_fault_spec_cli_loadgen_chaos_smoke(task):
    """Chaos mode end to end at smoke scale: injected step failures under
    retrying loadgen traffic -> 0 errors, absorbed retries counted, the
    bucket healed, and the final report says so."""
    import scripts.serve_loadgen as lg

    args = lg.parse_args([
        "--synthetic", f"{H},{N},{C}", "--method", "coda",
        "--workers", "4", "--sessions", "6", "--labels", "3",
        "--capacity", "6", "--max-wait-ms", "1",
        "--fault-spec", "step_raise:after=4", "--retries", "8",
        "--backoff-ms", "30", "--no-warm",
    ])
    report = lg.run_loadgen(args)
    assert report["n_errors"] == 0, report["errors"]
    assert report["n_retries"] >= 1                 # the fault was absorbed
    assert report["config"]["fault_spec"] == "step_raise:after=4"


# ---------------------------------------------------------------------------
# offline stream verification + the tier-1 fault-matrix gate
# ---------------------------------------------------------------------------

def test_replay_serve_cli_verdicts(task, tmp_path):
    """`cli replay-serve` verifies a record dir's session streams offline:
    clean streams PARITY (exit 0), a tampered stream DIVERGED (exit 2)."""
    from coda_tpu.serve.recovery import replay_serve_main
    from coda_tpu.telemetry import SessionRecorder

    d = str(tmp_path / "rec")
    a = _app(task, recorder=SessionRecorder(out_dir=d))
    try:
        for seed in range(2):
            _drive(a, seed=seed, rounds=2)
    finally:
        a.drain(timeout=5)                          # writes close markers
    assert replay_serve_main([d, "--synthetic", f"{H},{N},{C}"]) == 0

    # flip one recorded oracle answer -> that stream must DIVERGE
    fn = sorted(f for f in os.listdir(d) if f.startswith("session_"))[0]
    path = os.path.join(d, fn)
    rows = [json.loads(ln) for ln in open(path) if ln.strip()]
    for r in rows:
        if r.get("do_update"):
            r["label"] = (int(r["label"]) + 1) % C
            break
    with open(path, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in rows)
    assert replay_serve_main([d, "--synthetic", f"{H},{N},{C}"]) == 2


def test_fault_matrix_tier1_gate():
    """Tier-1 wiring of scripts/check_fault_matrix.py: the in-process
    fault matrix (crash scenarios excluded — the SIGKILL test above
    covers process death with a full bitwise control comparison) runs
    clean: every injection point ends in a recovered session or an
    attributable, digest-checked detection."""
    import scripts.check_fault_matrix as m

    results = m.run_matrix(skip_crash=True)
    assert sorted(results) == ["demote_during_label", "record_eio",
                               "slow_step", "step_nan", "step_raise"]
    violations = [v for vs in results.values() for v in vs]
    assert violations == [], violations
