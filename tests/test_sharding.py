"""Sharded-execution parity: the same jitted program on 1 chip vs. an
8-virtual-device mesh must produce the same experiment trace.

SURVEY.md section 4(c): the TPU build's distributed story is sharding the
``(H, N, C)`` tensor over a ``jax.sharding.Mesh`` (N over the ``data`` axis —
the context-parallel analog — and H over ``model``), with XLA inserting the
collectives. These tests pin that the sharded program computes the *same
numbers* as the single-device one (the only semantics the reference's
single-GPU implementation defines), on the CPU backend with 8 virtual
devices (conftest sets ``xla_force_host_platform_device_count=8``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from coda_tpu.data import make_synthetic_task
from coda_tpu.engine import run_experiment
from coda_tpu.oracle import true_losses
from coda_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    mesh_from_spec,
    preds_sharding,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _sharded_task(task, mesh):
    preds = jax.device_put(task.preds, preds_sharding(mesh))
    labels = jax.device_put(task.labels, NamedSharding(mesh, P(DATA_AXIS)))
    return type(task)(preds=preds, labels=labels, name=task.name)


def _trace(selector_factory, task, iters=8, seed=0, **kw):
    sel = selector_factory(task.preds, **kw)
    res = run_experiment(sel, task, iters=iters, seed=seed)
    return (
        np.asarray(res.chosen_idx),
        np.asarray(res.best_model),
        np.asarray(res.regret),
    )


@pytest.mark.parametrize("mesh_spec", ["data=8", "data=4,model=2", "model=4"])
@pytest.mark.parametrize("method", ["coda", "iid", "uncertainty",
                                    "activetesting", "vma", "model_picker"])
def test_sharded_trace_matches_single_device(method, mesh_spec):
    from coda_tpu.selectors import SELECTOR_FACTORIES

    # shapes divisible by every mesh axis size used above
    task = make_synthetic_task(seed=7, H=8, N=64, C=4)
    mesh = mesh_from_spec(mesh_spec)

    idx1, best1, reg1 = _trace(SELECTOR_FACTORIES[method], task)
    idx8, best8, reg8 = _trace(
        SELECTOR_FACTORIES[method], _sharded_task(task, mesh)
    )

    np.testing.assert_array_equal(idx1, idx8)
    np.testing.assert_array_equal(best1, best8)
    np.testing.assert_allclose(reg1, reg8, rtol=0, atol=0)


def test_sharded_pbest_matches(tiny_task):
    """The P(best) kernel with H sharded over the model axis (exclusive
    log-CDF product = psum of per-model log-CDFs) matches replicated."""
    from coda_tpu.ops.pbest import compute_pbest

    H = 8
    a = jnp.abs(jax.random.normal(jax.random.PRNGKey(0), (5, H))) * 10 + 1
    b = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (5, H))) * 10 + 1

    mesh = make_mesh(model=8)
    sh = NamedSharding(mesh, P(None, MODEL_AXIS))
    out1 = jax.jit(compute_pbest)(a, b)
    out8 = jax.jit(compute_pbest)(jax.device_put(a, sh), jax.device_put(b, sh))
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out8),
                               rtol=0, atol=0)


def test_sharded_eig_scores_match():
    """EIG scoring with N sharded over the data axis matches replicated."""
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import eig_scores

    task = make_synthetic_task(seed=11, H=4, N=64, C=4)
    mesh = make_mesh(data=8)

    def scores_for(preds):
        sel = make_coda(preds, CODAHyperparams(eig_chunk=64, num_points=64))
        state = jax.jit(sel.init)(jax.random.PRNGKey(0))
        hard = jnp.argmax(preds, -1).T.astype(jnp.int32)
        return np.asarray(
            jax.jit(
                lambda s: eig_scores(s.dirichlets, s.pi_hat, s.pi_hat_xi,
                                     hard, num_points=64, chunk=64)
            )(state)
        )

    s1 = scores_for(task.preds)
    s8 = scores_for(jax.device_put(task.preds, preds_sharding(mesh)))
    # the pi-hat einsum reduces over the sharded N axis; partial-sum order
    # differs under psum, so raw floats carry ~1e-7 reduction noise — the
    # selection argmax (the semantics that matter) must still agree
    np.testing.assert_allclose(s1, s8, atol=1e-6)
    assert int(s1.argmax()) == int(s8.argmax())


def test_mesh_spec_parsing_and_errors():
    m = mesh_from_spec("data=4,model=2")
    assert m.shape == {DATA_AXIS: 4, MODEL_AXIS: 2}
    with pytest.raises(ValueError, match="unknown mesh axis"):
        mesh_from_spec("bogus=2")
    with pytest.raises(ValueError, match="needs"):
        make_mesh(data=64)
