"""Sharded-execution parity: the same jitted program on 1 chip vs. an
8-virtual-device mesh must produce the same experiment trace.

SURVEY.md section 4(c): the TPU build's distributed story is sharding the
``(H, N, C)`` tensor over a ``jax.sharding.Mesh`` (N over the ``data`` axis —
the context-parallel analog — and H over ``model``), with XLA inserting the
collectives. These tests pin that the sharded program computes the *same
numbers* as the single-device one (the only semantics the reference's
single-GPU implementation defines), on the CPU backend with 8 virtual
devices (conftest sets ``xla_force_host_platform_device_count=8``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from coda_tpu.data import make_synthetic_task
from coda_tpu.engine import run_experiment
from coda_tpu.oracle import true_losses
from coda_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    mesh_from_spec,
    preds_sharding,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _sharded_task(task, mesh):
    preds = jax.device_put(task.preds, preds_sharding(mesh))
    labels = jax.device_put(task.labels, NamedSharding(mesh, P(DATA_AXIS)))
    return type(task)(preds=preds, labels=labels, name=task.name)


def _trace(selector_factory, task, iters=8, seed=0, **kw):
    """Run via the preds-as-ARGUMENT path (run_seeds_compiled's pattern).

    A jit-CAPTURED sharded array is silently committed to one device (XLA
    constant-folds the closure), so closure-style runs would de-shard and
    make these parity tests vacuous; passing the tensor as a traced argument
    keeps GSPMD sharding live through the whole experiment.
    """
    from coda_tpu.engine.loop import make_batched_experiment_fn

    fn = make_batched_experiment_fn(lambda p: selector_factory(p, **kw),
                                    iters=iters)
    keys = jnp.stack([jax.random.PRNGKey(seed)])
    res = jax.jit(fn)(task.preds, task.labels, keys)
    return (
        np.asarray(res.chosen_idx)[0],
        np.asarray(res.best_model)[0],
        np.asarray(res.regret)[0],
    )


@pytest.mark.parametrize("mesh_spec", ["data=8", "data=4,model=2", "model=4"])
@pytest.mark.parametrize("method", ["coda", "iid", "uncertainty",
                                    "activetesting", "vma"])
def test_sharded_trace_matches_single_device(method, mesh_spec):
    from coda_tpu.selectors import SELECTOR_FACTORIES

    # shapes divisible by every mesh axis size used above
    task = make_synthetic_task(seed=7, H=8, N=64, C=4)
    mesh = mesh_from_spec(mesh_spec)

    idx1, best1, reg1 = _trace(SELECTOR_FACTORIES[method], task)
    idx8, best8, reg8 = _trace(
        SELECTOR_FACTORIES[method], _sharded_task(task, mesh)
    )

    np.testing.assert_array_equal(idx1, idx8)
    np.testing.assert_array_equal(best1, best8)
    np.testing.assert_allclose(reg1, reg8, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("mesh_spec", ["data=8", "data=4,model=2", "model=4"])
def test_sharded_modelpicker_scores_match(mesh_spec):
    """ModelPicker's parity claim under sharding is at the SCORE level: with
    a uniform initial posterior many points tie at the exact minimum entropy,
    and psum partial-sum ordering over a sharded H axis legitimately perturbs
    which entries are bitwise equal — the tied pick is stochastic by the
    method's own semantics (always_stochastic). So assert the expected
    entropies match within reduction noise and the achieved minimum is the
    same; the trace-equality claim is covered by the deterministic methods
    above."""
    import jax.numpy as jnp

    from coda_tpu.selectors.modelpicker import expected_entropies

    task = make_synthetic_task(seed=7, H=8, N=64, C=4)
    mesh = mesh_from_spec(mesh_spec)
    post = jnp.full((8,), 1.0 / 8, jnp.float32)

    def ent_of(preds):
        hard = jnp.argmax(preds, -1).T.astype(jnp.int32)
        return np.asarray(jax.jit(
            lambda h: expected_entropies(h, post, (1 - 0.46) / 0.46, 4)
        )(hard))

    e1 = ent_of(task.preds)
    e8 = ent_of(jax.device_put(task.preds, preds_sharding(mesh)))
    np.testing.assert_allclose(e1, e8, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(e1.min(), e8.min(), rtol=1e-6)


def test_sharded_pbest_matches(tiny_task):
    """The P(best) kernel with H sharded over the model axis (exclusive
    log-CDF product = psum of per-model log-CDFs) matches replicated."""
    from coda_tpu.ops.pbest import compute_pbest

    H = 8
    a = jnp.abs(jax.random.normal(jax.random.PRNGKey(0), (5, H))) * 10 + 1
    b = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (5, H))) * 10 + 1

    mesh = make_mesh(model=8)
    sh = NamedSharding(mesh, P(None, MODEL_AXIS))
    out1 = jax.jit(compute_pbest)(a, b)
    out8 = jax.jit(compute_pbest)(jax.device_put(a, sh), jax.device_put(b, sh))
    # tolerance, not bitwise: the sharded psum of per-model log-CDFs
    # reassociates the fp32 reduction, so partial-sum order legitimately
    # drifts from the single-device sum by ~1 ulp (measured max abs diff
    # 5.96e-8 on the 8-way virtual mesh); the kernel semantics are
    # otherwise identical
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out8),
                               rtol=1e-6, atol=1e-7)


def test_sharded_eig_scores_match():
    """EIG scoring with N sharded over the data axis matches replicated."""
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import eig_scores

    task = make_synthetic_task(seed=11, H=4, N=64, C=4)
    mesh = make_mesh(data=8)

    def scores_for(preds):
        sel = make_coda(preds, CODAHyperparams(eig_chunk=64, num_points=64))
        state = jax.jit(sel.init)(jax.random.PRNGKey(0))
        hard = jnp.argmax(preds, -1).T.astype(jnp.int32)
        return np.asarray(
            jax.jit(
                lambda s: eig_scores(s.dirichlets, s.pi_hat, s.pi_hat_xi,
                                     hard, num_points=64, chunk=64)
            )(state)
        )

    s1 = scores_for(task.preds)
    s8 = scores_for(jax.device_put(task.preds, preds_sharding(mesh)))
    # the pi-hat einsum reduces over the sharded N axis; partial-sum order
    # differs under psum, so raw floats carry ~1e-7 reduction noise — the
    # selection argmax (the semantics that matter) must still agree
    np.testing.assert_allclose(s1, s8, atol=1e-6)
    assert int(s1.argmax()) == int(s8.argmax())


def test_mesh_spec_parsing_and_errors():
    m = mesh_from_spec("data=4,model=2")
    assert m.shape == {DATA_AXIS: 4, MODEL_AXIS: 2}
    with pytest.raises(ValueError, match="unknown mesh axis"):
        mesh_from_spec("bogus=2")
    with pytest.raises(ValueError, match="needs"):
        make_mesh(data=64)


def test_imagenet_scale_aot_memory_analysis():
    """SURVEY.md §5: at ImageNet scale (M=500 x N=50k x C=1000 fp32 ~ 100 GB,
    reference ``paper/fig3.py:129-193``) sharding is mandatory. AOT-lower the
    full jitted experiment (init + one labeling round) with the prediction
    tensor sharded over an 8-device mesh and prove, via XLA's own
    ``memory_analysis``, that per-device argument bytes are ~1/8 of the
    tensor (no replication) and temps stay bounded — a compiled artifact, not
    prose. No execution happens (the tensor never exists)."""
    from coda_tpu.engine.loop import make_batched_experiment_fn
    from coda_tpu.selectors import CODAHyperparams, make_coda

    H, N, C = 500, 50_000, 1000
    preds_bytes = 4 * H * N * C                      # 100 GB
    mesh = make_mesh(data=4, model=2)

    fn = make_batched_experiment_fn(
        lambda p: make_coda(p, CODAHyperparams(eig_chunk=512)), iters=1)
    args = (
        jax.ShapeDtypeStruct((H, N, C), jnp.float32,
                             sharding=preds_sharding(mesh)),
        jax.ShapeDtypeStruct((N,), jnp.int32,
                             sharding=NamedSharding(mesh, P(DATA_AXIS))),
        jax.ShapeDtypeStruct((1, 2), jnp.uint32,
                             sharding=NamedSharding(mesh, P())),
    )
    compiled = jax.jit(fn).lower(*args).compile()
    ma = compiled.memory_analysis()
    assert ma is not None
    per_dev_args = ma.argument_size_in_bytes
    # the (H, N, C) argument dominates: per-device share must be ~1/8 of the
    # full tensor — replication anywhere would show up as >=2x this
    assert per_dev_args < preds_bytes / 8 * 1.10, (
        f"args {per_dev_args / 2**30:.2f} GiB/device vs "
        f"{preds_bytes / 8 / 2**30:.2f} GiB expected shard"
    )
    assert per_dev_args > preds_bytes / 8 * 0.95
    # temps must scale with the SHARD, not the global tensor: on this
    # backend XLA keeps ~2 transposed copies of the local preds shard for
    # the init einsums (confusion matrices, pi-hat), which is fine — a
    # replication bug would instead add >= the full 100 GB (8 shards)
    shard = preds_bytes / 8
    assert ma.temp_size_in_bytes < 3.0 * shard, (
        f"temps {ma.temp_size_in_bytes / 2**30:.2f} GiB/device vs shard "
        f"{shard / 2**30:.2f} GiB — temps should be O(shard)"
    )


def test_incremental_cache_shards_over_data_axis():
    """The incremental-EIG state cache (C, N, H) must inherit the data-axis
    sharding of the prediction tensor on its N axis — replicating it would
    double every device's footprint at headline scale (the cache is as
    large as preds)."""
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = make_synthetic_task(seed=9, H=8, N=64, C=4)
    mesh = mesh_from_spec("data=4,model=2")
    preds = jax.device_put(task.preds, preds_sharding(mesh))

    # production pattern: preds is a traced jit ARGUMENT (run_seeds_compiled),
    # so GSPMD propagates its sharding into the state pytree
    @jax.jit
    def init_of(p, key):
        return make_coda(p, CODAHyperparams(eig_mode="incremental",
                                            eig_chunk=64)).init(key)

    state = init_of(preds, jax.random.PRNGKey(0))
    assert state.pbest_hyp is not None
    spec = state.pbest_hyp.sharding.spec
    # the N axis (dim 1 of the (C, N, H) layout) split over the data mesh
    # axis; no dimension may be sharded in a way that replicates N per
    # device
    assert len(spec) > 1 and (
        spec[1] == DATA_AXIS or spec[1] == (DATA_AXIS,)), spec
    n_shard_bytes = state.pbest_hyp.addressable_shards[0].data.nbytes
    total = 4 * 64 * 4 * 8
    assert n_shard_bytes <= total // 4, (n_shard_bytes, total)


def test_auto_resolver_large_c_shapes():
    """The auto tier resolution at the REAL large-C shapes (pure function of
    (hp, H, N, C) — no tensors exist here): the VERDICT-item-4 config
    resolves factored once seed replicas share the chip, and the
    C=1000 x H=2000+ HF zero-shot pool pushes past the table budget into
    rowscan."""
    from coda_tpu.selectors import CODAHyperparams
    from coda_tpu.selectors.coda import resolve_eig_mode

    # H=128, N=4096, C=1000: cache + delta layout is 3.9 GiB and the
    # DENSE (H, C, C) posterior the budget now charges adds 0.5 GiB —
    # past the 4 GiB budget, so the dense representation resolves
    # factored even for one replica; the sparse:32 posterior (34 MB) is
    # exactly what keeps this shape on the incremental tier
    assert resolve_eig_mode(CODAHyperparams(), 128, 4096, 1000) == "factored"
    assert resolve_eig_mode(CODAHyperparams(posterior="sparse:32"),
                            128, 4096, 1000) == "incremental"
    assert resolve_eig_mode(
        CODAHyperparams(n_parallel=4), 128, 4096, 1000) == "factored"
    # ImageNet-scale reference config: 93 GiB cache is out, 1.9 GiB of
    # tables fit -> factored; a second replica pushes into rowscan
    assert resolve_eig_mode(
        CODAHyperparams(), 500, 50_000, 1000) == "factored"
    assert resolve_eig_mode(
        CODAHyperparams(n_parallel=2), 500, 50_000, 1000) == "rowscan"
    # the big HF pool blows the table budget outright -> rowscan
    assert resolve_eig_mode(
        CODAHyperparams(), 2048, 50_000, 1000) == "rowscan"


@pytest.mark.parametrize("tier,budgets", [
    # shrink the auto budgets so the SAME resolver logic routes this
    # CPU-executable C=1000 config to each large-C tier end-to-end
    ("factored", {"_INCR_CACHE_MAX_BYTES": 1 << 20}),
    ("rowscan", {"_INCR_CACHE_MAX_BYTES": 1 << 20,
                 "_TABLES_MAX_BYTES": 1 << 20}),
])
def test_large_c_sharded_execution_parity(tier, budgets, monkeypatch):
    """VERDICT item 4: a C=1000-class experiment EXECUTES sharded
    data=4,model=2 and matches the single-device trace, with the auto
    resolver (not a pin) choosing the large-C tier.

    The true large-C shapes are not CPU-executable (factored EIG at
    H=128, N=4096, C=1000 is ~1e14 FLOPs/round), so the executed config is
    C=1000 at CPU-feasible H/N with the auto BUDGETS shrunk until the
    resolver makes the same choice it makes at scale (the shape-level
    routing at the real sizes is pinned by test_auto_resolver_large_c_shapes
    above, and the 100 GB AOT memory analysis covers the compiled artifact).
    """
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors import coda as coda_mod

    for name, val in budgets.items():
        monkeypatch.setattr(coda_mod, name, val)

    H, N, C = 16, 512, 1000
    hp = CODAHyperparams(eig_chunk=128, num_points=32)
    assert coda_mod.resolve_eig_mode(hp, H, N, C) == tier

    # sharpness: at C=1000 the default 4.0 leaves ~3% softmax mass on the
    # predicted class — predictions are near-uniform, every EIG score is
    # fp32 noise (~1e-6) and argmax parity is meaningless. 12.0 gives
    # confident models and real EIG signal (margins >> reduction noise).
    task = make_synthetic_task(seed=13, H=H, N=N, C=C, sharpness=12.0)
    mesh = mesh_from_spec("data=4,model=2")

    idx1, best1, reg1 = _trace(lambda p, **kw: make_coda(p, hp), task,
                               iters=4)
    idx8, best8, reg8 = _trace(lambda p, **kw: make_coda(p, hp),
                               _sharded_task(task, mesh), iters=4)

    # chosen-point parity is at SET level: psum reduction noise can move a
    # pair of near-tie scores across the isclose tie boundary, swapping the
    # order of two picks (observed on the rowscan tier: steps 3/4 transpose
    # points 12/302) — the framework's own semantics flag such picks
    # stochastic. The labeled set must agree, and the per-step observables
    # (best model, regret) must agree at every step where the two runs have
    # seen the same evidence — after a transposed pick they legitimately
    # differ for a step, then must reconverge once the sets realign.
    np.testing.assert_array_equal(np.sort(idx1), np.sort(idx8))
    same_evidence = np.array([set(idx1[:k + 1]) == set(idx8[:k + 1])
                              for k in range(len(idx1))])
    assert same_evidence[-1], "labeled sets never realigned"
    np.testing.assert_array_equal(best1[same_evidence], best8[same_evidence])
    np.testing.assert_allclose(reg1[same_evidence], reg8[same_evidence],
                               rtol=1e-6, atol=1e-7)


def test_sharded_pallas_trace_matches_single_device():
    """The shard_map'd pallas scoring/fused-refresh path (shard_spec +
    eig_backend='pallas') must reproduce the single-device jnp trace on a
    data=8 mesh — the v5e-8 fast-path configuration (VERDICT r4 item 2).
    Interpret-mode pallas per shard on the virtual CPU mesh; the same
    code Mosaic-compiles per chip on real TPUs."""
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = make_synthetic_task(seed=13, H=6, N=64, C=4)
    mesh = mesh_from_spec("data=8")
    sharded = _sharded_task(task, mesh)

    idx1, best1, reg1 = _trace(
        lambda p: make_coda(p, CODAHyperparams(eig_mode="incremental")),
        task)
    idx8, best8, reg8 = _trace(
        lambda p: make_coda(p, CODAHyperparams(
            eig_mode="incremental", eig_backend="pallas",
            shard_spec="data=8")),
        sharded)
    np.testing.assert_array_equal(idx1, idx8)
    np.testing.assert_array_equal(best1, best8)
    np.testing.assert_allclose(reg1, reg8, atol=1e-7)


def test_sharded_pallas_scores_stay_sharded():
    """The sharded pallas scoring pass must emit data-sharded scores (no
    device gathers the full cache): check the out sharding of the
    shard_map'd kernel directly."""
    from coda_tpu.ops.pallas_eig import eig_scores_cache_pallas_sharded

    mesh = mesh_from_spec("data=8")
    C, N, H = 4, 64, 6
    key = jax.random.PRNGKey(0)
    rows = jax.nn.softmax(jax.random.normal(key, (C, H)), axis=-1)
    hyp = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(1), (C, N, H)), axis=-1)
    pi = jnp.full((C,), 1.0 / C)
    pi_xi = jnp.full((N, C), 1.0 / C)
    hyp_sh = jax.device_put(
        hyp, NamedSharding(mesh, P(None, DATA_AXIS, None)))
    pi_xi_sh = jax.device_put(pi_xi, NamedSharding(mesh, P(DATA_AXIS, None)))

    out = jax.jit(lambda r, h, p, px: eig_scores_cache_pallas_sharded(
        r, h, p, px, mesh=mesh, interpret=True))(rows, hyp_sh, pi, pi_xi_sh)
    spec = out.sharding.spec
    assert spec and spec[0] in (DATA_AXIS, (DATA_AXIS,)), spec

    from coda_tpu.selectors.coda import eig_scores_from_cache
    ref = eig_scores_from_cache(rows, hyp, pi, pi_xi)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-6)
