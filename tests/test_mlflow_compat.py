"""Round-trip the native tracking store through the MLflow client.

The real-client test skips when mlflow isn't installed (it is not in TPU
images); wherever it is, it verifies the full reference workflow — our
store -> export -> ``mlflow ui``-ready backend — with the
experiment/parent/child layout and metric series intact (reference
``README.md:45``, ``scripts/aggregate_results.py`` consumers). The
stub-client test below exercises the exporter's logic everywhere.
"""

from __future__ import annotations

import importlib.util
import os

import numpy as np
import pytest


def _export_module():
    spec = importlib.util.spec_from_file_location(
        "export_mlflow",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "export_mlflow.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_export_roundtrip(tmp_path):
    mlflow = pytest.importorskip("mlflow")
    from coda_tpu.tracking import TrackingStore

    db = str(tmp_path / "native.sqlite")
    store = TrackingStore(db)
    regret = np.linspace(0.5, 0.0, 5)
    with store.run("taskA", "taskA-coda",
                   params={"method": "coda"}) as parent:
        with store.run("taskA", "taskA-coda-0", parent=parent,
                       params={"seed": 0, "stochastic": False}) as r:
            r.log_metric_series("regret", regret, start_step=1)
    store.close()

    dest = f"sqlite:///{tmp_path / 'mlflow.sqlite'}"
    counts = _export_module().export(db, dest, progress=lambda s: None)
    assert counts == {"experiments": 1, "runs": 2, "metrics": 5}

    client = mlflow.tracking.MlflowClient(tracking_uri=dest)
    exp = client.get_experiment_by_name("taskA")
    assert exp is not None
    runs = client.search_runs([exp.experiment_id])
    by_name = {r.data.tags["mlflow.runName"]: r for r in runs}
    assert set(by_name) == {"taskA-coda", "taskA-coda-0"}
    child = by_name["taskA-coda-0"]
    assert (child.data.tags["mlflow.parentRunId"]
            == by_name["taskA-coda"].info.run_id)
    assert child.data.params["seed"] == "0"
    history = client.get_metric_history(child.info.run_id, "regret")
    assert [m.step for m in history] == [1, 2, 3, 4, 5]
    np.testing.assert_allclose([m.value for m in history], regret, atol=1e-9)
    assert child.info.status == "FINISHED"


def test_export_logic_with_stub_client(tmp_path, monkeypatch):
    """Exercise every exporter decision without mlflow installed: parent
    runs exported before children, parentRunId remapped to the DEST run
    ids, controlled tags set exactly once, params/metrics forwarded, runs
    terminated with their source status. (The real-client round-trip test
    above still runs wherever mlflow exists.)"""
    import sys
    import types

    from coda_tpu.tracking import TrackingStore

    # a tiny native store: one experiment, parent + 2 seed children
    db = str(tmp_path / "native.sqlite")
    store = TrackingStore(db)
    with store.run("expA", "expA-coda", params={"method": "coda"}) as parent:
        for s in range(2):
            with store.run("expA", f"expA-coda-{s}", parent=parent,
                           params={"seed": s}) as r:
                r.log_metric_series("regret", [0.5, 0.25], start_step=1)
    store.close()

    class StubClient:
        def __init__(self, tracking_uri):
            self.uri = tracking_uri
            self.created = []       # (exp, tags, run_name) in call order
            self.batches = {}
            self.terminated = {}
            self._n = 0

        def get_experiment_by_name(self, name):
            return None

        def create_experiment(self, name):
            return f"dest-exp-{name}"

        def create_run(self, exp, start_time, tags, run_name):
            self._n += 1
            rid = f"dest-run-{self._n}"
            self.created.append((exp, dict(tags), run_name, rid))
            info = types.SimpleNamespace(run_id=rid)
            return types.SimpleNamespace(info=info)

        def log_batch(self, run_id, metrics, params, tags):
            self.batches[run_id] = (list(metrics), list(params), list(tags))

        def set_terminated(self, run_id, status, end_time):
            self.terminated[run_id] = status

    holder = {}

    def client_factory(tracking_uri):
        holder["client"] = StubClient(tracking_uri)
        return holder["client"]

    fake_mlflow = types.ModuleType("mlflow")
    fake_entities = types.ModuleType("mlflow.entities")
    fake_entities.Metric = lambda k, v, ts, step: ("metric", k, v, step)
    fake_entities.Param = lambda k, v: ("param", k, v)
    fake_entities.RunTag = lambda k, v: ("tag", k, v)
    fake_tracking = types.ModuleType("mlflow.tracking")
    fake_tracking.MlflowClient = client_factory
    fake_mlflow.entities = fake_entities
    fake_mlflow.tracking = fake_tracking
    for name, mod in [("mlflow", fake_mlflow),
                      ("mlflow.entities", fake_entities),
                      ("mlflow.tracking", fake_tracking)]:
        monkeypatch.setitem(sys.modules, name, mod)

    export = _export_module().export

    counts = export(db, "stub://dest", progress=lambda s: None)
    client = holder["client"]
    assert counts == {"experiments": 1, "runs": 3, "metrics": 4}

    # parent first; children carry the REMAPPED dest parent id. Children
    # are keyed by run name, not creation order: equal-millisecond start
    # times make the source ORDER BY a tie, and tie order is SQLite's
    (exp0, tags0, name0, rid0) = client.created[0]
    assert exp0 == "dest-exp-expA" and name0 == "expA-coda"
    assert "mlflow.parentRunId" not in tags0
    by_name = {name: (tags, rid) for _, tags, name, rid in client.created[1:]}
    assert set(by_name) == {"expA-coda-0", "expA-coda-1"}
    for tags, _ in by_name.values():
        assert tags["mlflow.parentRunId"] == rid0

    # params/metrics forwarded; every run terminated with its source status
    rid_seed0 = by_name["expA-coda-0"][1]
    metrics1, params1, tags_b1 = client.batches[rid_seed0]
    assert ("param", "seed", "0") in params1
    assert [m[3] for m in metrics1] == [1, 2]  # steps
    assert set(client.terminated) == {r[3] for r in client.created}
    assert all(s == "FINISHED" for s in client.terminated.values())
