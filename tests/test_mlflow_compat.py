"""Round-trip the native tracking store through the real MLflow client.

Skipped when mlflow isn't installed (it is not in TPU images); wherever it
is, this verifies the full reference workflow — our store -> export ->
``mlflow ui``-ready backend — with the experiment/parent/child layout and
metric series intact (reference ``README.md:45``,
``scripts/aggregate_results.py`` consumers).
"""

from __future__ import annotations

import importlib.util
import os

import numpy as np
import pytest

mlflow = pytest.importorskip("mlflow")


def _export_module():
    spec = importlib.util.spec_from_file_location(
        "export_mlflow",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "export_mlflow.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_export_roundtrip(tmp_path):
    from coda_tpu.tracking import TrackingStore

    db = str(tmp_path / "native.sqlite")
    store = TrackingStore(db)
    regret = np.linspace(0.5, 0.0, 5)
    with store.run("taskA", "taskA-coda",
                   params={"method": "coda"}) as parent:
        with store.run("taskA", "taskA-coda-0", parent=parent,
                       params={"seed": 0, "stochastic": False}) as r:
            r.log_metric_series("regret", regret, start_step=1)
    store.close()

    dest = f"sqlite:///{tmp_path / 'mlflow.sqlite'}"
    counts = _export_module().export(db, dest, progress=lambda s: None)
    assert counts == {"experiments": 1, "runs": 2, "metrics": 5}

    client = mlflow.tracking.MlflowClient(tracking_uri=dest)
    exp = client.get_experiment_by_name("taskA")
    assert exp is not None
    runs = client.search_runs([exp.experiment_id])
    by_name = {r.data.tags["mlflow.runName"]: r for r in runs}
    assert set(by_name) == {"taskA-coda", "taskA-coda-0"}
    child = by_name["taskA-coda-0"]
    assert (child.data.tags["mlflow.parentRunId"]
            == by_name["taskA-coda"].info.run_id)
    assert child.data.params["seed"] == "0"
    history = client.get_metric_history(child.info.run_id, "regret")
    assert [m.step for m in history] == [1, 2, 3, 4, 5]
    np.testing.assert_allclose([m.value for m in history], regret, atol=1e-9)
    assert child.info.status == "FINISHED"
