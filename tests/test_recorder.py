"""Decision flight recorder + deterministic replay + divergence triage.

Covers the record/replay contract end to end:

  * recording does not perturb the decision trajectory (recorded
    ExperimentResult bitwise equals the unrecorded program's);
  * a record replays BITWISE on the same backend for every selector in
    ``selectors/`` (the acceptance contract of ``cli replay``);
  * an injected near-tie perturbation is localized to the correct first
    divergent round and classified as a tie-break flip; a beyond-tolerance
    score perturbation classifies as a score delta;
  * the CLI record -> replay -> triage loop works through
    ``python -m coda_tpu.cli`` entry points;
  * suite runs write per-(family, method) record streams that pass the
    versioned schema check;
  * the serving layer streams per-session decision rows
    (``GET /session/{id}/trace``) and counts them on /stats;
  * ``Telemetry`` flushes artifacts via context manager AND via the atexit
    fallback when a run dies mid-flight (subprocess crash test);
  * ``scripts/check_record_schema.py`` is wired into tier-1: clean
    artifacts pass, tampered ones fail;
  * recorder overhead on the compiled loop stays ≤5% (slow bench).
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from coda_tpu.data import make_synthetic_task
from coda_tpu.engine.loop import run_seeds_compiled, run_seeds_recorded
from coda_tpu.engine.replay import (
    compare_records,
    compare_seed,
    format_triage,
    replay_main,
    verify_replay,
)
from coda_tpu.losses import accuracy_loss
from coda_tpu.telemetry.recorder import (
    RECORD_SCHEMA_VERSION,
    SESSION_SCHEMA_VERSION,
    RunRecord,
    SessionRecorder,
    dataset_digest,
    environment_fingerprint,
)

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _factories():
    """Every selector family in ``selectors/`` as (name, preds->Selector)."""
    from coda_tpu.selectors import (
        CODAHyperparams,
        make_activetesting,
        make_coda,
        make_iid,
        make_modelpicker,
        make_uncertainty,
        make_vma,
    )

    hp = CODAHyperparams(eig_chunk=48, num_points=64)
    hp_direct = CODAHyperparams(eig_chunk=48, num_points=64,
                                eig_mode="direct")
    return [
        ("iid", lambda p: make_iid(p)),
        ("uncertainty", lambda p: make_uncertainty(p)),
        ("activetesting", lambda p: make_activetesting(p, budget=12)),
        ("vma", lambda p: make_vma(p, budget=12)),
        ("model_picker", lambda p: make_modelpicker(p)),
        ("coda", lambda p: make_coda(p, hp)),
        ("coda_direct", lambda p: make_coda(p, hp_direct)),
    ]


def _record_run(factory, task, iters=12, seeds=2, trace_k=5,
                run_meta=None):
    res, aux = run_seeds_recorded(factory, task.preds, task.labels,
                                  iters=iters, seeds=seeds, trace_k=trace_k)
    fp = environment_fingerprint(dataset=task, knobs={})
    return RunRecord.from_result(
        res, aux, fp, run=dict({"task": task.name, "iters": iters,
                                "seeds": seeds}, **(run_meta or {})))


# ---------------------------------------------------------------------------
# core contract: recording is transparent, replay is bitwise
# ---------------------------------------------------------------------------

def test_recording_does_not_perturb_trajectory(tiny_task):
    from coda_tpu.selectors import CODAHyperparams, make_coda

    fac = lambda p: make_coda(p, CODAHyperparams(eig_chunk=48,
                                                 num_points=64))
    base = run_seeds_compiled(fac, tiny_task.preds, tiny_task.labels,
                              iters=10, seeds=3)
    rec, _aux = run_seeds_recorded(fac, tiny_task.preds, tiny_task.labels,
                                   iters=10, seeds=3, trace_k=5)
    for name, a, b in zip(base._fields, base, rec):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), name


@pytest.mark.parametrize("name", [f[0] for f in _factories()])
def test_replay_bitwise_parity_per_selector(name, tiny_task, tmp_path):
    """Every selector's record replays bitwise on CPU — the same-backend
    replay contract, through save/load (so the on-disk roundtrip is part
    of the pinned path)."""
    factory = dict(_factories())[name]
    record = _record_run(factory, tiny_task)
    record.save(tmp_path / name)
    loaded = RunRecord.load(str(tmp_path / name))
    report = verify_replay(loaded, factory, tiny_task.preds,
                           tiny_task.labels, score_tol=0.0)
    assert report.parity, format_triage(report)


def test_record_trace_contents(tiny_task):
    """Per-round provenance semantics: keys match the scan's split table,
    the gap is top1-top2, the posterior digest is present for CODA."""
    import jax

    from coda_tpu.selectors import CODAHyperparams, make_coda

    fac = lambda p: make_coda(p, CODAHyperparams(eig_chunk=48,
                                                 num_points=64))
    record = _record_run(fac, tiny_task, iters=8, seeds=1, trace_k=4)
    arr = record.seed_arrays(0)
    # the recorded round keys ARE the experiment's key table
    key = jax.random.PRNGKey(0)
    _, _, k_scan = jax.random.split(key, 3)
    keys = np.asarray(jax.random.split(k_scan, 8), np.uint32)
    np.testing.assert_array_equal(arr["round_key"], keys)
    np.testing.assert_allclose(
        arr["runner_up_gap"],
        arr["topk_score"][:, 0] - arr["topk_score"][:, 1], rtol=0, atol=0)
    assert np.isfinite(arr["pbest_max"]).all()
    assert (arr["pbest_max"] > 0).all() and (arr["pbest_max"] <= 1.0).all()
    # top-k scores are descending
    assert (np.diff(arr["topk_score"], axis=1) <= 0).all()


# ---------------------------------------------------------------------------
# divergence triage
# ---------------------------------------------------------------------------

def test_injected_tiebreak_flip_localized_and_classified(tiny_task,
                                                         tmp_path):
    """A single-ulp score perturbation that flips the pick at round r is
    triaged to exactly round r and classified tie-break-flip."""
    from coda_tpu.selectors import CODAHyperparams, make_coda

    fac = lambda p: make_coda(p, CODAHyperparams(eig_chunk=48,
                                                 num_points=64))
    record = _record_run(fac, tiny_task)
    r = 6
    arrays = {k: v.copy() for k, v in record.arrays.items()}
    # the flip: the runner-up wins by one ulp — scores move less than any
    # meaningful tolerance, only the argmax order changes
    top = arrays["topk_score"][0, r, 0]
    arrays["topk_score"][0, r, 0] = np.nextafter(top, np.float32(np.inf))
    arrays["topk_idx"][0, r, [0, 1]] = arrays["topk_idx"][0, r, [1, 0]]
    arrays["chosen_idx"][0, r] = arrays["topk_idx"][0, r, 0]
    perturbed = RunRecord(meta=record.meta, arrays=arrays)
    report = compare_records(record, perturbed, score_tol=1e-6)
    s0 = report.seeds[0]
    assert not s0.parity
    assert s0.first_divergent_round == r
    assert s0.classification == "tie-break-flip"
    assert s0.quantity in ("chosen_idx", "true_class")
    assert report.seeds[1].parity  # untouched seed stays clean


def test_injected_score_delta_classified(tiny_task):
    """A beyond-tolerance score change classifies as score-delta at its
    round even when the pick does not change."""
    from coda_tpu.selectors import CODAHyperparams, make_coda

    fac = lambda p: make_coda(p, CODAHyperparams(eig_chunk=48,
                                                 num_points=64))
    record = _record_run(fac, tiny_task)
    r = 3
    arrays = {k: v.copy() for k, v in record.arrays.items()}
    arrays["topk_score"][0, r, 1] += 1e-3
    perturbed = RunRecord(meta=record.meta, arrays=arrays)
    report = compare_records(record, perturbed, score_tol=1e-5)
    s0 = report.seeds[0]
    assert s0.first_divergent_round == r
    assert s0.classification == "score-delta"
    assert s0.quantity == "topk_score"
    assert s0.quantities["topk_score"]["max_abs_delta"] == \
        pytest.approx(1e-3, rel=1e-3)


def test_posterior_drift_classified(tiny_task):
    """Decisions equal, posterior digest moved -> posterior-drift (the
    bf16-cache / update-chain numerics signature)."""
    from coda_tpu.selectors import CODAHyperparams, make_coda

    fac = lambda p: make_coda(p, CODAHyperparams(eig_chunk=48,
                                                 num_points=64))
    record = _record_run(fac, tiny_task)
    r = 4
    arrays = {k: v.copy() for k, v in record.arrays.items()}
    arrays["pbest_max"][0, r:] += 5e-3
    perturbed = RunRecord(meta=record.meta, arrays=arrays)
    report = compare_records(record, perturbed, score_tol=1e-4)
    s0 = report.seeds[0]
    assert s0.first_divergent_round == r
    assert s0.classification == "posterior-drift"


def test_compare_records_mismatched_widths(tiny_task):
    """Different --record-topk compares the common top-k prefix; different
    seed counts compare common seeds and SAY so instead of claiming full
    parity; --against auto tolerance keys off the two records' fingerprints
    (not the current host's backend)."""
    from coda_tpu.engine.replay import _auto_tol
    from coda_tpu.selectors import make_iid

    fac = lambda p: make_iid(p)
    wide = _record_run(fac, tiny_task, iters=6, seeds=3, trace_k=6)
    narrow = _record_run(fac, tiny_task, iters=6, seeds=1, trace_k=3)
    report = compare_records(wide, narrow, score_tol=0.0)
    assert report.parity  # common prefix of the identical run
    assert report.meta["seed_count_mismatch"] == {"a": 3, "b": 1,
                                                  "compared": 1}
    assert report.meta["trace_k_compared"] == 3
    assert "WARNING" in format_triage(report)

    # --against auto tol: two same-fingerprint records -> bitwise; a
    # fake other-backend record -> the cross-backend contract
    assert _auto_tol(wide, {}, against=wide) == 0.0
    other = RunRecord(meta=json.loads(json.dumps(narrow.meta)),
                      arrays=narrow.arrays)
    other.meta["fingerprint"]["backend"] = "tpu"
    from coda_tpu.telemetry.recorder import CROSS_BACKEND_SCORE_TOL

    assert _auto_tol(wide, {}, against=other) == CROSS_BACKEND_SCORE_TOL


def test_max_delta_reports_nan_vs_finite():
    """A posterior digest present in one record and absent (NaN) in the
    other is a structural divergence and must surface as inf, not 0."""
    rec = {"chosen_idx": np.array([1, 2], np.int32),
           "pbest_max": np.array([0.5, 0.6], np.float32)}
    rep = {"chosen_idx": np.array([1, 2], np.int32),
           "pbest_max": np.array([0.5, np.nan], np.float32)}
    s = compare_seed(rec, rep, score_tol=1e-3)
    assert not s.parity
    assert s.first_divergent_round == 1
    assert s.quantities["pbest_max"]["max_abs_delta"] == np.inf


def test_compare_seed_nan_and_inf_semantics():
    """NaN digests (methods without a posterior) and -inf masked scores are
    equal to themselves at every tolerance — absence is not divergence."""
    base = {
        "chosen_idx": np.array([1, 2], np.int32),
        "pbest_max": np.array([np.nan, np.nan], np.float32),
        "topk_score": np.array([[1.0, -np.inf], [0.5, -np.inf]],
                               np.float32),
    }
    for tol in (0.0, 1e-6):
        assert compare_seed(base, {k: v.copy() for k, v in base.items()},
                            score_tol=tol).parity


# ---------------------------------------------------------------------------
# CLI loop: record -> replay -> triage
# ---------------------------------------------------------------------------

def test_cli_record_then_replay_roundtrip(tmp_path):
    from coda_tpu import cli

    rec_dir = str(tmp_path / "rec")
    cli.main(["--synthetic", "5,40,3", "--iters", "6", "--seeds", "2",
              "--method", "model_picker", "--no-mlflow",
              "--record-dir", rec_dir])
    assert os.path.isfile(os.path.join(rec_dir, "record.json"))
    meta = json.load(open(os.path.join(rec_dir, "record.json")))
    assert meta["schema_version"] == RECORD_SCHEMA_VERSION
    fp = meta["fingerprint"]
    assert fp["backend"] == "cpu"
    assert "threefry_partitionable" in fp
    assert fp["dataset"]["digest"]
    assert fp["knobs"]["method"] == "model_picker"
    # bitwise replay through the subcommand (exit code 0 = parity)
    assert cli.main(["replay", rec_dir]) == 0
    # --against itself is trivially parity
    assert replay_main([rec_dir, "--against", rec_dir]) == 0


def test_cli_replay_detects_tampered_record(tmp_path):
    from coda_tpu import cli

    rec_dir = str(tmp_path / "rec")
    cli.main(["--synthetic", "5,40,3", "--iters", "6", "--seeds", "1",
              "--method", "uncertainty", "--no-mlflow",
              "--record-dir", rec_dir])
    record = RunRecord.load(rec_dir)
    record.arrays["chosen_idx"][0, 2] = \
        record.arrays["topk_idx"][0, 2, 1]
    record.save(rec_dir)
    assert cli.main(["replay", rec_dir]) == 2  # divergence verdict code


def test_dataset_digest_guards_replay(tmp_path):
    """Replaying a record against different data fails loudly."""
    from coda_tpu import cli

    rec_dir = str(tmp_path / "rec")
    cli.main(["--synthetic", "5,40,3", "--iters", "4", "--seeds", "1",
              "--method", "iid", "--no-mlflow", "--record-dir", rec_dir])
    record = RunRecord.load(rec_dir)
    record.meta["fingerprint"]["dataset"]["digest"] = "0" * 16
    record.save(rec_dir)
    with pytest.raises((ValueError, SystemExit)):
        replay_main([rec_dir])
    # explicit escape hatch still replays (and still reaches a verdict)
    assert replay_main([rec_dir, "--allow-digest-mismatch"]) in (0, 2)


def test_digest_stability():
    t1 = make_synthetic_task(seed=0, H=4, N=32, C=3)
    t2 = make_synthetic_task(seed=0, H=4, N=32, C=3)
    t3 = make_synthetic_task(seed=1, H=4, N=32, C=3)
    assert dataset_digest(t1.preds, t1.labels) == \
        dataset_digest(t2.preds, t2.labels)
    assert dataset_digest(t1.preds, t1.labels) != \
        dataset_digest(t3.preds, t3.labels)


# ---------------------------------------------------------------------------
# suite streams + schema checker wiring (tier-1, like check_clocks)
# ---------------------------------------------------------------------------

def _load_schema_checker():
    spec = importlib.util.spec_from_file_location(
        "check_record_schema",
        os.path.join(REPO, "scripts", "check_record_schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_suite_record_streams_and_schema(tmp_path):
    from coda_tpu.engine.suite import SuiteRunner

    tasks = [make_synthetic_task(seed=i, H=4, N=40, C=3,
                                 name=f"alpha_{i}") for i in range(2)]
    rec_root = str(tmp_path / "streams")
    runner = SuiteRunner(iters=4, seeds=2, record_dir=rec_root,
                         record_topk=3)
    results = runner.run_batched([tasks], ["iid", "model_picker"],
                                 progress=lambda s: None)
    # one record per task under per-(family, method) streams
    for method in ("iid", "model_picker"):
        for t in ("alpha_0", "alpha_1"):
            d = os.path.join(rec_root, f"alpha__{method}", t)
            assert os.path.isfile(os.path.join(d, "record.json")), d
    # recorded run results match an unrecorded runner bitwise
    plain = SuiteRunner(iters=4, seeds=2)
    base = plain.run_batched([tasks], ["iid", "model_picker"],
                             progress=lambda s: None)
    for key in base:
        for a, b in zip(results[key], base[key]):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), key
    # the streams validate against the versioned schema
    mod = _load_schema_checker()
    assert mod.check_tree(rec_root) == {}
    assert mod.check_tree.last_checked == 4
    # two same-family records diff clean under the record-vs-record path
    a = RunRecord.load(os.path.join(rec_root, "alpha__iid", "alpha_0"))
    assert compare_records(a, a, score_tol=0.0).parity


def test_check_record_schema_flags_drift(tmp_path):
    """Tier-1 wiring of scripts/check_record_schema.py: unversioned or
    field-drifted records fail, clean ones pass."""
    from coda_tpu.selectors import make_iid

    record = _record_run(lambda p: make_iid(p),
                         make_synthetic_task(seed=0, H=4, N=32, C=3),
                         iters=4, seeds=1, trace_k=3)
    good = tmp_path / "good"
    record.save(str(good))
    mod = _load_schema_checker()
    assert mod.check_tree(str(tmp_path)) == {}

    # unversioned record
    meta = json.load(open(good / "record.json"))
    del meta["schema_version"]
    bad1 = tmp_path / "bad1"
    os.makedirs(bad1)
    json.dump(meta, open(bad1 / "record.json", "w"))
    import shutil

    shutil.copy(good / "rounds.npz", bad1 / "rounds.npz")
    # field drift: an array vanished, another appeared
    bad2 = tmp_path / "bad2"
    arrays = {k: v for k, v in record.arrays.items()}
    arrays["surprise"] = np.zeros(3)
    del arrays["topk_score"]
    RunRecord(meta=record.meta, arrays=arrays).save(str(bad2))

    bad = mod.check_tree(str(tmp_path))
    assert any("schema_version" in v for v in bad.get("bad1", []))
    assert any("topk_score" in v for v in bad.get("bad2", []))
    assert any("unversioned field drift" in v for v in bad.get("bad2", []))
    assert mod.main([str(tmp_path)]) == 1
    assert mod.main([str(good)]) == 0

    # session stream validation
    stream = tmp_path / "good" / "session_ab12.jsonl"
    with open(stream, "w") as f:
        f.write(json.dumps({"v": SESSION_SCHEMA_VERSION,
                            "kind": "session_meta"}) + "\n")
        f.write(json.dumps({"v": SESSION_SCHEMA_VERSION, "n_labeled": 0,
                            "do_update": False, "labeled_idx": None,
                            "label": None, "prob": None, "request_id": None,
                            "next_idx": 1, "next_prob": 0.5, "best": 0,
                            "stochastic": False, "pbest_max": 0.5,
                            "pbest_entropy": 0.9}) + "\n")
    assert mod.check_tree(str(good)) == {}
    # a v2 row missing the fields the version bump added IS drift
    with open(stream, "a") as f:
        f.write(json.dumps({"v": SESSION_SCHEMA_VERSION, "n_labeled": 1,
                            "do_update": True, "next_idx": 2,
                            "next_prob": 0.5, "best": 0}) + "\n")
    assert any("missing fields" in v
               for v in mod.check_tree(str(good)).get(
                   "session_ab12.jsonl", []))
    with open(stream, "w") as f:
        f.write(json.dumps({"v": SESSION_SCHEMA_VERSION,
                            "kind": "session_meta"}) + "\n")
    with open(stream, "a") as f:
        f.write(json.dumps({"next_idx": 2}) + "\n")  # no version stamp
    assert any("version stamp" in v
               for v in mod.check_tree(str(good)).get(
                   "session_ab12.jsonl", []))


# ---------------------------------------------------------------------------
# serving streams
# ---------------------------------------------------------------------------

def test_serve_session_trace_stream(tmp_path):
    from coda_tpu.serve.server import ServeApp
    from coda_tpu.serve.state import SelectorSpec

    task = make_synthetic_task(seed=0, H=4, N=32, C=3)
    app = ServeApp(capacity=4, spec=SelectorSpec.create("iid"),
                   recorder=SessionRecorder(out_dir=str(tmp_path)))
    app.add_task(task.name, task.preds)
    app.start()
    try:
        s = app.open_session()
        sid = s["session"]
        for _ in range(3):
            s = app.label(sid, label=0, idx=s["idx"])
        tr = app.trace(sid)
        assert tr["n_labeled"] == 3
        assert len(tr["rounds"]) == 4  # start dispatch + 3 labels
        assert tr["rounds"][0]["do_update"] is False
        assert tr["rounds"][1]["do_update"] is True
        assert tr["rounds"][1]["labeled_idx"] is not None
        assert all(r["v"] == SESSION_SCHEMA_VERSION for r in tr["rounds"])
        stats = app.stats()
        assert stats["record_rows_written"] >= 4
        assert "records_written" in stats and "replay_verified" in stats
        # crash-safe stream on disk, one meta line + one row per dispatch
        fp = os.path.join(str(tmp_path), f"session_{sid}.jsonl")
        lines = [json.loads(x) for x in open(fp).read().splitlines()]
        assert lines[0]["kind"] == "session_meta"
        assert len(lines) == 5
        mod = _load_schema_checker()
        assert mod.check_tree(str(tmp_path)) == {}
        app.close_session(sid)
        assert app.recorder.history(sid) is None
    finally:
        app.drain(timeout=5.0)


# ---------------------------------------------------------------------------
# telemetry flush: context manager + crash atexit fallback
# ---------------------------------------------------------------------------

def test_telemetry_context_manager_flushes(tmp_path):
    from coda_tpu.telemetry import Telemetry

    out = str(tmp_path / "tele")
    with Telemetry(out_dir=out, install_hooks=False) as tele:
        tele.counter("ctx_test_total").inc()
    for fn in ("trace.json", "telemetry.json", "metrics.prom"):
        assert os.path.isfile(os.path.join(out, fn)), fn

    # exceptional exit still flushes, and does not swallow the error
    out2 = str(tmp_path / "tele2")
    with pytest.raises(RuntimeError):
        with Telemetry(out_dir=out2, install_hooks=False):
            raise RuntimeError("mid-flight death")
    assert os.path.isfile(os.path.join(out2, "telemetry.json"))


def test_crash_mid_run_still_yields_valid_artifacts(tmp_path):
    """A run that dies on an unhandled exception still leaves telemetry
    artifacts (atexit fallback) and schema-valid record streams (per-row
    JSONL flush) behind."""
    out = str(tmp_path / "crash")
    script = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
from coda_tpu.telemetry import SessionRecorder, Telemetry

tele = Telemetry(out_dir={out!r})
tele.counter("crash_total").inc()
rec = SessionRecorder(out_dir={out!r})
rec.open("dead0", meta={{"task": "t"}})
rec.append("dead0", {{"n_labeled": 0, "do_update": False,
                      "labeled_idx": None, "label": None, "prob": None,
                      "request_id": None, "next_idx": 3, "next_prob": 0.5,
                      "best": 1, "stochastic": False, "pbest_max": 0.5,
                      "pbest_entropy": 0.9}})
raise RuntimeError("simulated mid-run crash")
"""
    proc = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode != 0  # it really crashed
    assert "simulated mid-run crash" in proc.stderr
    for fn in ("trace.json", "telemetry.json", "metrics.prom",
               "session_dead0.jsonl"):
        assert os.path.isfile(os.path.join(out, fn)), (fn, proc.stderr)
    tele = json.load(open(os.path.join(out, "telemetry.json")))
    assert tele["metrics"]["crash_total"]["values"][""] == 1.0
    mod = _load_schema_checker()
    assert mod.check_tree(out) == {}
    assert mod.check_tree.last_checked == 1


# ---------------------------------------------------------------------------
# overhead bench (slow: wall-clock measurement)
# ---------------------------------------------------------------------------

def test_recorder_overhead_under_five_percent():
    """The trace tap adds ≤5% to the compiled loop: the extra work per
    round is O(N) top-k + O(H) digest against the selector's
    O(N·C·H)-class scoring.

    The ≤5% bound is asserted on XLA's own cost analysis (FLOPs +
    transcendentals of the compiled executables) — deterministic, unlike
    wall clock on this container, where two fresh compiles of the SAME
    program differ by up to ~8% in codegen quality. Wall is still
    measured (interleaved min-of-7) as a gross-regression tripwire and
    committed as evidence in BENCH_RECORDER_CPU_r08.json (measured
    +0.1%..+3.2% across shapes)."""
    import jax

    from coda_tpu.selectors import CODAHyperparams, make_coda

    # a shape where the EIG scoring chain dominates (the realistic regime:
    # the recorder's O(N) top-k + O(H) digest amortize against O(N·C·H)
    # scoring); measured +0.1%..+3.2% on this container across shapes
    task = make_synthetic_task(seed=0, H=32, N=4096, C=8)
    fac = lambda p: make_coda(p, CODAHyperparams(eig_chunk=4096,
                                                 num_points=128))
    # the persistent compile cache must not skew the comparison: a
    # cache-DESERIALIZED executable runs measurably faster than the same
    # HLO fresh-compiled in-process (observed 3.4x on this container), so
    # whichever side happened to be cached by an earlier session would win
    # unfairly — force both sides to fresh codegen
    prev_cache = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)

    def once(fn):
        out = fn()
        # recorded runs return (ExperimentResult, aux); plain runs
        # return the (NamedTuple) result directly
        (out if hasattr(out, "regret") else out[0]) \
            .regret.block_until_ready()

    run_base = lambda: run_seeds_compiled(
        fac, task.preds, task.labels, iters=30, seeds=2,
        loss_fn=accuracy_loss)
    run_rec = lambda: run_seeds_recorded(
        fac, task.preds, task.labels, iters=30, seeds=2,
        loss_fn=accuracy_loss, trace_k=8)
    try:
        # the deterministic bound: compiled-executable cost analysis
        def cost(fn, trace_k):
            from coda_tpu.engine.loop import make_batched_experiment_fn
            from coda_tpu.losses import LOSS_FNS

            f = make_batched_experiment_fn(fac, 30, LOSS_FNS["acc"],
                                           trace_k=trace_k)
            keys = jax.numpy.stack([jax.random.PRNGKey(s)
                                    for s in range(2)])
            compiled = jax.jit(f).lower(task.preds, task.labels,
                                        keys).compile()
            (ca,) = compiled.cost_analysis() \
                if isinstance(compiled.cost_analysis(), list) \
                else (compiled.cost_analysis(),)
            return (float(ca.get("flops", 0.0))
                    + float(ca.get("transcendentals", 0.0)))

        c_base = cost(fac, 0)
        c_rec = cost(fac, 8)
        flop_overhead = c_rec / c_base - 1.0
        assert flop_overhead <= 0.05, (
            f"recorder op-count overhead {flop_overhead:.2%} exceeds the "
            f"5% bound (base {c_base:.3e}, recorded {c_rec:.3e})")

        once(run_base)  # warm-up: pay both compiles outside the timing
        once(run_rec)
        # interleaved min-of-7: back-to-back pairs cancel the container's
        # load drift, min strips scheduler noise from each side
        base_walls, rec_walls = [], []
        for _ in range(7):
            t0 = time.perf_counter()
            once(run_base)
            base_walls.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            once(run_rec)
            rec_walls.append(time.perf_counter() - t0)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_cache)
    base, recorded = min(base_walls), min(rec_walls)
    overhead = recorded / base - 1.0
    # gross tripwire only: per-compile codegen variance on this container
    # is larger than the 5% claim, which the cost analysis above pins
    assert overhead <= 0.25, (
        f"recorder wall overhead {overhead:.1%} — far beyond the expected "
        f"few percent (base {base:.3f}s, recorded {recorded:.3f}s)")
