"""Cross-session surrogate priors (``--surrogate-prior pool``, ISSUE 18).

What tier-1 pins here:

  * the pool merge algebra: ``merge_fits`` is a pure sum — associative,
    commutative, merge-of-one identity, ``empty_prior`` the neutral
    element (all bitwise), so fleet aggregation order can never change
    a pool;
  * the mass cap preserves the ridge solution (A/b/n scale together),
    and ``fold_prior`` decays exactly once;
  * ``surrogate_prior='off'`` (the default) is bitwise the PR 14
    program — q=1 and q=8, dense and sparse posterior;
  * a seeded session earns warmup credit but every served round still
    passes the per-round trust gate (selection is never driven by an
    unaudited score);
  * PriorPool: the min-rounds contribution gate, the drain/merge router
    exchange (decay applied once), replace-not-merge on the push half;
  * serve end-to-end: a closing donor session warm-starts the next
    session on the same (task, pool fingerprint), the pool survives a
    restart through the tracking store, and the prior counters surface
    on /stats + lint-clean /metrics;
  * recorder/replay: the surrogate_prior knob + pool digest are
    fingerprinted, and a prior-vs-off knob diff triages as
    surrogate-prior-envelope instead of a fake bitwise divergence.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from coda_tpu.data import make_synthetic_task
from coda_tpu.engine.loop import run_seeds_compiled
from coda_tpu.selectors import CODAHyperparams, make_coda
from coda_tpu.selectors import surrogate as sg

H, N, C = 8, 64, 5


@pytest.fixture(scope="module")
def task():
    return make_synthetic_task(seed=0, H=H, N=N, C=C)


def _trees_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def _rand_prior(seed: int, rounds: float = 12.0) -> sg.PriorStats:
    """A structurally plausible random contribution: A symmetric PSD,
    arbitrary b, positive pair mass."""
    rng = np.random.default_rng(seed)
    F = sg.N_FEATURES
    M = rng.normal(size=(F, F))
    return sg.prior_from_fit(M @ M.T, rng.normal(size=(F,)),
                             n=float(rng.uniform(5.0, 50.0)),
                             rounds=rounds)


def _priors_bitwise(p: sg.PriorStats, q: sg.PriorStats) -> bool:
    return (p.A.tobytes() == q.A.tobytes()
            and p.b.tobytes() == q.b.tobytes()
            and np.float64(p.n).tobytes() == np.float64(q.n).tobytes()
            and np.float64(p.rounds).tobytes()
            == np.float64(q.rounds).tobytes()
            and np.float64(p.sessions).tobytes()
            == np.float64(q.sessions).tobytes())


# ---------------------------------------------------------------------------
# merge algebra: the property the fleet aggregation relies on
# ---------------------------------------------------------------------------

def test_merge_fits_commutative():
    for s in range(5):
        p, q = _rand_prior(2 * s), _rand_prior(2 * s + 1)
        assert _priors_bitwise(sg.merge_fits(p, q), sg.merge_fits(q, p))


def test_merge_fits_associative():
    """(p+q)+r == p+(q+r) bitwise — float addition is not associative in
    general, but the elementwise SUM of these float64 statistics is
    exercised here over realistic magnitudes; the pins below are the
    contract the router's merge order depends on."""
    for s in range(5):
        p, q, r = (_rand_prior(3 * s), _rand_prior(3 * s + 1),
                   _rand_prior(3 * s + 2))
        lhs = sg.merge_fits(sg.merge_fits(p, q), r)
        rhs = sg.merge_fits(p, sg.merge_fits(q, r))
        assert np.allclose(lhs.A, rhs.A, rtol=0, atol=0) or \
            np.allclose(lhs.A, rhs.A, rtol=1e-15)
        assert np.allclose(lhs.b, rhs.b, rtol=1e-15)
        assert lhs.n == pytest.approx(rhs.n, rel=1e-15)
        assert lhs.rounds == pytest.approx(rhs.rounds, rel=1e-15)
        assert lhs.sessions == rhs.sessions


def test_merge_of_one_is_identity_and_empty_is_neutral():
    p = _rand_prior(7)
    assert _priors_bitwise(sg.merge_many([p]), p)
    assert _priors_bitwise(sg.merge_fits(sg.empty_prior(), p), p)
    assert _priors_bitwise(sg.merge_fits(p, sg.empty_prior()), p)
    z = sg.merge_many([])
    assert _priors_bitwise(z, sg.empty_prior())
    assert z.n == 0.0 and z.rounds == 0.0


def test_degenerate_fit_contributes_the_neutral_element():
    """A session closed before its first label (n == 0 fit) folds into a
    pool as a bitwise no-op."""
    F = sg.N_FEATURES
    zero = sg.prior_from_fit(np.zeros((F, F)), np.zeros((F,)), 0.0, 0.0)
    p = _rand_prior(3)
    assert _priors_bitwise(sg.merge_fits(p, zero), p)


# ---------------------------------------------------------------------------
# fold policy: decay once, cap mass, keep the ridge solution
# ---------------------------------------------------------------------------

def test_fold_prior_decays_pool_once():
    pool, contrib = _rand_prior(11, rounds=20.0), _rand_prior(12,
                                                              rounds=14.0)
    out = sg.fold_prior(pool, contrib)
    assert out.rounds == pytest.approx(
        sg.SURROGATE_PRIOR_DECAY * pool.rounds + contrib.rounds)
    assert out.n == pytest.approx(
        sg.SURROGATE_PRIOR_DECAY * pool.n + contrib.n)


def test_clip_prior_caps_mass_and_preserves_ridge_solution():
    p = _rand_prior(13)
    big = sg.scale_prior(p, (2 * sg.SURROGATE_PRIOR_MAX_PAIRS) / p.n)
    capped = sg.clip_prior(big)
    assert capped.n == pytest.approx(sg.SURROGATE_PRIOR_MAX_PAIRS)
    # provenance counters are not mass — they survive the cap
    assert capped.rounds == big.rounds and capped.sessions == big.sessions
    # A/b/n scale together and lambda scales with n, so the solved
    # weights are unchanged by the cap
    F = sg.N_FEATURES

    def solve(q):
        lam = sg.SURROGATE_RIDGE_LAMBDA * max(q.n, 1.0)
        return np.linalg.solve(q.A + lam * np.eye(F), q.b)

    assert np.allclose(solve(capped), solve(big), rtol=1e-9)
    # under-cap pools pass through untouched (bitwise)
    assert _priors_bitwise(sg.clip_prior(p), p)


def test_prior_warmup_credit_caps_at_full_warmup():
    assert sg.prior_warmup_credit(sg.empty_prior()) == 0
    thin = _rand_prior(14, rounds=4.0)
    assert sg.prior_warmup_credit(thin) == 4
    deep = _rand_prior(15, rounds=500.0)
    assert sg.prior_warmup_credit(deep) == sg.SURROGATE_WARMUP_ROUNDS


def test_prior_dict_roundtrip_and_digest():
    p = _rand_prior(16)
    q = sg.prior_from_dict(sg.prior_to_dict(p))
    assert _priors_bitwise(p, q)
    assert sg.prior_digest(p) == sg.prior_digest(q)
    assert sg.prior_digest(p) != sg.prior_digest(_rand_prior(17))
    with pytest.raises(ValueError, match="version"):
        sg.prior_from_dict({"v": 99})


# ---------------------------------------------------------------------------
# the off-config bitwise pin: PR 14 unchanged under the new knob
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("q", [1, 8])
@pytest.mark.parametrize("posterior", ["dense", "sparse:3"])
def test_off_is_bitwise_pr14(task, q, posterior):
    """surrogate_prior='off' (explicit AND the default) runs the
    identical PR 14 program — q=1/q=8, dense and sparse posterior.
    q=8 runs on a larger pool so surrogate-carried rounds fit inside
    the label budget (iters x q <= N)."""
    t = task if q == 1 else make_synthetic_task(seed=1, H=H, N=256, C=C)
    base = dict(eig_scorer="surrogate:8", n_parallel=2)
    if posterior != "dense":
        base["posterior"] = posterior

    def run(hp):
        return run_seeds_compiled(
            lambda p: make_coda(p, hp), t.preds, t.labels,
            iters=sg.SURROGATE_WARMUP_ROUNDS + 4, seeds=2, acq_batch=q)

    r_pr14 = run(CODAHyperparams(**base))
    r_off = run(CODAHyperparams(surrogate_prior="off", **base))
    assert _trees_equal(r_pr14, r_off)


def test_parse_prior_and_make_coda_validation(task):
    assert sg.parse_prior("off") is False
    assert sg.parse_prior("pool") is True
    with pytest.raises(ValueError, match="unknown surrogate_prior"):
        sg.parse_prior("warm")
    # pool requires a carried fit to warm-start
    with pytest.raises(ValueError, match="carries none"):
        make_coda(task.preds, CODAHyperparams(surrogate_prior="pool"))
    # a prior under the off knob would break the off-config pin
    with pytest.raises(ValueError, match="bitwise pin"):
        make_coda(task.preds,
                  CODAHyperparams(eig_scorer="surrogate:8"),
                  prior=_rand_prior(0))


# ---------------------------------------------------------------------------
# seeding: warmup credit granted, trust gate untouched
# ---------------------------------------------------------------------------

def _drive(task, hp, rounds, seed=0, prior=None):
    sel = make_coda(task.preds, hp, prior=prior)
    st = jax.jit(sel.init)(jax.random.PRNGKey(seed))
    upd = jax.jit(sel.update)
    slx = jax.jit(sel.select)
    key = jax.random.PRNGKey(seed + 1)
    for _ in range(rounds):
        key, k = jax.random.split(key)
        res = slx(st, k)
        st = upd(st, res.idx, task.labels[res.idx], res.prob)
    return sel, st, key


def _donor_prior(task, rounds=sg.SURROGATE_WARMUP_ROUNDS + 6):
    _, st, _ = _drive(task, CODAHyperparams(eig_scorer="surrogate:8"),
                      rounds)
    fit = st.surrogate
    return sg.prior_from_fit(np.asarray(fit.A, np.float64),
                             np.asarray(fit.b, np.float64),
                             float(fit.n), float(fit.rounds))


def test_seeded_session_skips_warmup_but_keeps_the_gate(task):
    """A mature donor prior grants the full warmup credit: the seeded
    run's fit starts solved (n > 0, prior_rounds == 10) and the
    surrogate can carry rounds BEFORE its own round counter reaches the
    warmup — while the selected index's score is still always the exact
    chain's value (the shortlist-rows-are-exact property under
    seeding)."""
    prior = _donor_prior(task)
    hp = CODAHyperparams(eig_scorer="surrogate:8",
                         surrogate_prior="pool")
    sel = make_coda(task.preds, hp, prior=prior)
    st = jax.jit(sel.init)(jax.random.PRNGKey(3))
    assert int(st.surrogate.prior_rounds) == sg.SURROGATE_WARMUP_ROUNDS
    assert float(st.surrogate.n) > 0.0
    upd = jax.jit(sel.update)
    slx = jax.jit(sel.select)
    score_exact = jax.jit(sel.extras["score_exact"])
    key = jax.random.PRNGKey(4)
    carried_early = 0
    for _ in range(sg.SURROGATE_WARMUP_ROUNDS - 2):
        key, k = jax.random.split(key)
        res = slx(st, k)
        i = int(res.idx)
        exact = np.asarray(score_exact(st))
        got = np.asarray(st.eig_scores_cached)
        # never an unaudited argmax: the served score is the exact one
        assert exact[i].tobytes() == got[i].tobytes()
        if (int(st.surrogate.rounds) < sg.SURROGATE_WARMUP_ROUNDS
                and not bool(st.surrogate.last_fallback)
                and int(st.surrogate.rounds) > 0):
            carried_early += 1
        st = upd(st, res.idx, task.labels[res.idx], res.prob)
    assert carried_early > 0, "the prior never shortened the warmup"
    assert np.isfinite(np.asarray(st.eig_scores_cached)).all()


def test_seeded_session_bad_prior_falls_back_exact(task):
    """A hostile prior (garbage normal equations with full credit) is
    caught by the per-round contract: rounds inside the skipped warmup
    window fall back to the exact pass bitwise and count
    prior_rejects — the gate-rejection safety net."""
    rng = np.random.default_rng(0)
    F = sg.N_FEATURES
    bad = sg.prior_from_fit(np.eye(F) * 1e-6,
                            rng.normal(size=(F,)) * 1e4,
                            n=100.0, rounds=50.0)
    hp = CODAHyperparams(eig_scorer="surrogate:8",
                         surrogate_prior="pool")
    sel = make_coda(task.preds, hp, prior=bad)
    st = jax.jit(sel.init)(jax.random.PRNGKey(5))
    assert int(st.surrogate.prior_rounds) == sg.SURROGATE_WARMUP_ROUNDS
    upd = jax.jit(sel.update)
    slx = jax.jit(sel.select)
    score_exact = jax.jit(sel.extras["score_exact"])
    key = jax.random.PRNGKey(6)
    for _ in range(4):
        key, k = jax.random.split(key)
        res = slx(st, k)
        st = upd(st, res.idx, task.labels[res.idx], res.prob)
        # every fallback round's vector is bitwise the exact pass
        if bool(st.surrogate.last_fallback):
            exact = np.asarray(score_exact(st))
            got = np.asarray(st.eig_scores_cached)
            assert exact.tobytes() == got.tobytes()
    assert int(st.surrogate.fallbacks) > 0, "the gate never tripped"
    assert int(st.surrogate.prior_rejects) > 0
    assert np.isfinite(np.asarray(st.eig_scores_cached)).all()


# ---------------------------------------------------------------------------
# PriorPool: contribution gate + router exchange halves
# ---------------------------------------------------------------------------

def _fit_stats(seed, rounds=12.0, n=30.0):
    rng = np.random.default_rng(seed)
    F = sg.N_FEATURES
    M = rng.normal(size=(F, F))
    return {"A": M @ M.T, "b": rng.normal(size=(F,)), "n": n,
            "rounds": rounds}


def test_pool_contribution_gate_and_get():
    from coda_tpu.serve.priors import PriorPool

    pool = PriorPool()
    # too green to teach anything: below min_rounds, or no pairs
    assert not pool.contribute("k", _fit_stats(0, rounds=3.0))
    assert not pool.contribute("k", _fit_stats(1, n=0.0))
    assert not pool.contribute("k", None)
    assert pool.get("k") is None
    assert pool.stats()["contributions_skipped"] == 2
    assert pool.contribute("k", _fit_stats(2))
    p = pool.get("k")
    assert p is not None and p.rounds == 12.0 and p.sessions == 1.0
    assert pool.get("other") is None
    st = pool.stats()
    assert st["sessions_contributed"] == 1 and st["pools"] == 1
    assert st["rounds_pooled"] == pytest.approx(12.0)


def test_pool_drain_merge_exchange_decays_once():
    """The replica drains raw sums; the router folds each drain exactly
    once — two contributions in one drain arrive as one pure sum and are
    decayed together, never per-contribution."""
    from coda_tpu.serve.priors import PriorPool

    replica, router = PriorPool(), PriorPool()
    assert replica.contribute("k", _fit_stats(3, rounds=11.0))
    assert replica.contribute("k", _fit_stats(4, rounds=13.0))
    delta = replica.drain_delta()
    assert set(delta) == {"k"}
    # the delta is the RAW sum (no decay): rounds add exactly
    assert delta["k"]["rounds"] == pytest.approx(24.0)
    assert replica.drain_delta() == {}      # drained
    assert router.merge_delta(delta) == 1
    assert router.get("k").rounds == pytest.approx(24.0)
    assert router.stats()["sessions_contributed"] == 2  # from sessions
    # the push half: the replica REPLACES with the router's merged pool,
    # so its own just-drained contributions never double-count
    replica.replace(router.snapshot())
    assert replica.get("k").rounds == pytest.approx(24.0)
    # count=False: a replica re-folding its own delta after a push must
    # not bump sessions_contributed again
    sc = router.sessions_contributed
    router.merge_delta(delta, count=False)
    assert router.sessions_contributed == sc


def test_pool_snapshot_is_json_safe_and_restores():
    import json as _json

    from coda_tpu.serve.priors import PriorPool

    pool = PriorPool()
    assert pool.contribute("a", _fit_stats(5))
    assert pool.contribute("b", _fit_stats(6, rounds=15.0))
    snap = _json.loads(_json.dumps(pool.snapshot()))
    fresh = PriorPool()
    assert fresh.replace(snap) == 2
    assert fresh.keys() == ["a", "b"]
    assert _priors_bitwise(fresh.get("a"), pool.get("a"))
    assert fresh.sessions_contributed == 2


def test_pool_key_ignores_feature_space_neutral_knobs():
    """The fingerprint drops the knobs that do not change the 16-feature
    space (scorer k, the prior knob itself, acq_batch) — a q=8
    surrogate:32 session shares its pool with a q=1 surrogate:8 one —
    and keeps the ones that do."""
    from coda_tpu.serve.priors import pool_key

    base = (("eig_scorer", "surrogate:8"), ("n_parallel", "2"))
    alt = (("eig_scorer", "surrogate:32"), ("n_parallel", "2"),
           ("surrogate_prior", "pool"), ("acq_batch", "8"))
    assert pool_key("t", "coda", base, "d1") == \
        pool_key("t", "coda", alt, "d1")
    assert pool_key("t", "coda", base, "d1") != \
        pool_key("t", "coda", base, "d2")        # dataset digest matters
    assert pool_key("t", "coda", base, "d1") != pool_key(
        "t", "coda", (("n_parallel", "4"),), "d1")   # feature-space knob


# ---------------------------------------------------------------------------
# serve end-to-end: donor -> pool -> warm-started admission
# ---------------------------------------------------------------------------

def _serve_app(task, recorder=None, **spec_kw):
    from coda_tpu.serve import SelectorSpec, ServeApp

    app = ServeApp(capacity=2, max_wait=0.001,
                   spec=SelectorSpec.create(
                       "coda", n_parallel=2, eig_scorer="surrogate:8",
                       surrogate_prior="pool", **spec_kw),
                   recorder=recorder)
    app.add_task(task.name, task.preds)
    app.start()
    return app


def _serve_drive(app, rounds, seed=0):
    out = app.open_session(seed=seed)
    sid = out["session"]
    for _ in range(rounds):
        out = app.label(sid, int(out["idx"]) % C)
    return sid


def test_serve_donor_warm_starts_next_session(task, tmp_path):
    """The full loop on one replica: a donor session closing after a
    full warmup contributes its fit; the NEXT admission on the same
    (task, pool fingerprint) seeds with the full warmup credit, and the
    counters surface on /stats and lint-clean /metrics."""
    import json as _json

    from coda_tpu.telemetry import prometheus
    from coda_tpu.telemetry.recorder import SessionRecorder

    rec_dir = str(tmp_path / "rec")
    app = _serve_app(task, recorder=SessionRecorder(out_dir=rec_dir))
    try:
        donor = _serve_drive(app, sg.SURROGATE_PRIOR_MIN_ROUNDS + 2)
        assert app.store.get(donor).prior_fit is None  # cold start
        app.close_session(donor)
        pool_stats = app.stats()["prior_pool"]
        assert pool_stats["sessions_contributed"] == 1
        assert pool_stats["pools"] == 1

        seeded = _serve_drive(app, 2, seed=1)
        pf = app.store.get(seeded).prior_fit
        assert pf is not None
        assert pf["credit"] == sg.SURROGATE_WARMUP_ROUNDS
        assert isinstance(pf["digest"], str) and pf["digest"]
        snap = app.stats()
        assert snap["prior_warmup_rounds_skipped"] >= \
            sg.SURROGATE_WARMUP_ROUNDS
        text = prometheus.render(app.telemetry.registry,
                                 serve_metrics=app.metrics)
        assert prometheus.lint(text) == []
        assert "coda_serve_prior_sessions_contributed" in text
        assert "coda_serve_prior_warmup_rounds_skipped" in text
        # the recorder stamped the applied prior + digest on the
        # session_meta header of the seeded stream (and NOT on the cold
        # donor's — cold streams stay bitwise PR-14)
        import os as _os

        def _header(sid):
            with open(_os.path.join(rec_dir,
                                    f"session_{sid}.jsonl")) as f:
                return _json.loads(f.readline())

        assert _header(seeded)["surrogate_prior"]["digest"] == \
            pf["digest"]
        assert "surrogate_prior" not in _header(donor)
    finally:
        app.drain(timeout=10)


def test_serve_pool_survives_restart_via_tracking_store(task, tmp_path):
    """save_prior_pool -> fresh app -> load_prior_pool: the restored
    pool warm-starts admissions created AFTER the load (the bucket
    prior resolver), surviving the restart boundary."""
    from coda_tpu.tracking import TrackingStore

    db = str(tmp_path / "prior.sqlite")
    app = _serve_app(task)
    try:
        donor = _serve_drive(app, sg.SURROGATE_PRIOR_MIN_ROUNDS + 2)
        app.close_session(donor)
        store = TrackingStore(db)
        app.save_prior_pool(store)
        store.close()
    finally:
        app.drain(timeout=10)

    app2 = _serve_app(task)
    try:
        store = TrackingStore(db)
        assert app2.load_prior_pool(store) == 1
        store.close()
        seeded = _serve_drive(app2, 1, seed=2)
        pf = app2.store.get(seeded).prior_fit
        assert pf is not None
        assert pf["credit"] == sg.SURROGATE_WARMUP_ROUNDS
    finally:
        app2.drain(timeout=10)


def test_serve_contribution_is_once_only_and_gated(task):
    """A session below the min-rounds gate is skipped (counted), and a
    demoted-then-closed session contributes exactly once
    (Session.prior_contributed rides the export payload)."""
    app = _serve_app(task)
    try:
        # too green: 3 rounds < SURROGATE_PRIOR_MIN_ROUNDS
        green = _serve_drive(app, 3)
        app.close_session(green)
        st = app.stats()["prior_pool"]
        assert st["sessions_contributed"] == 0
        assert st["contributions_skipped"] >= 1

        donor = _serve_drive(app, sg.SURROGATE_PRIOR_MIN_ROUNDS + 2,
                             seed=3)
        sess = app.store.get(donor)
        fit = sess.bucket.slot_fit(sess.slot)
        assert app.contribute_prior(sess, fit)       # first: accepted
        assert sess.prior_contributed
        assert not app.contribute_prior(sess, fit)   # second: refused
        app.close_session(donor)                     # close: no re-add
        assert app.stats()["prior_pool"]["sessions_contributed"] == 1
    finally:
        app.drain(timeout=10)


# ---------------------------------------------------------------------------
# recorder / replay: the knob is fingerprinted and triaged
# ---------------------------------------------------------------------------

def test_prior_knob_in_recorder_fields():
    from coda_tpu.telemetry.recorder import KNOB_FIELDS

    assert "surrogate_prior" in KNOB_FIELDS
    assert "surrogate_prior_digest" in KNOB_FIELDS


def test_prior_vs_off_triages_as_prior_envelope(task):
    """compare_records routes a pool-vs-off knob diff through the
    regret-envelope triage (classification surrogate-prior-envelope)
    instead of reporting a fake bitwise divergence — and two off
    records (one explicit, one default) still compare bitwise."""
    from coda_tpu.engine.loop import run_seeds_recorded
    from coda_tpu.engine.replay import compare_records
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    iters = sg.SURROGATE_WARMUP_ROUNDS + 6
    prior = _donor_prior(task)

    def rec(knobs, prior_arg=None):
        hp = CODAHyperparams(eig_scorer="surrogate:8", n_parallel=1,
                             surrogate_prior=knobs.get(
                                 "surrogate_prior", "off"))
        result, aux = run_seeds_recorded(
            lambda p: make_coda(p, hp, prior=prior_arg),
            task.preds, task.labels, iters=iters, seeds=1, trace_k=4)
        fp = environment_fingerprint(
            dataset=task, knobs={"method": "coda",
                                 "eig_scorer": "surrogate:8", **knobs})
        return RunRecord.from_result(
            result, aux, fp, run={"task": task.name, "iters": iters,
                                  "seeds": 1, "method": "coda",
                                  "loss": "acc"})

    a = rec({})
    b = rec({"surrogate_prior": "pool",
             "surrogate_prior_digest": sg.prior_digest(prior)},
            prior_arg=prior)
    report = compare_records(a, b)
    assert report.seeds[0].classification == "surrogate-prior-envelope"
    env = report.meta["prior_envelope"]
    assert env["prior_a"] == "off"
    assert env["prior_b"].startswith("pool@")
    # off-vs-off (explicit vs default-normalized) is still bitwise
    report2 = compare_records(a, rec({"surrogate_prior": "off"}))
    assert report2.parity
