"""Tests for the auxiliary subsystems: debug viz, profiling, artifact
logging, multi-host init (single-process no-op), and the --debug-viz /
--profile-dir CLI paths (SURVEY.md §5 — all new capability; the reference
has none of these)."""

from __future__ import annotations

import os

import numpy as np
import pytest


def test_plot_bar_and_series_render():
    from coda_tpu.utils.viz import fig_to_png, plot_bar, plot_series

    png = fig_to_png(plot_bar([0.1, 0.7, 0.2], title="t", highlight=1))
    assert png[:4] == b"\x89PNG"
    png2 = fig_to_png(plot_series([[1, 2], [3, 4]], labels=["a", "b"]))
    assert png2[:4] == b"\x89PNG"


def test_step_timer_rates():
    from coda_tpu.utils.profiling import StepTimer

    t = StepTimer()
    with t.span("work", steps=10):
        pass
    s = t.summary()["work"]
    assert s["steps"] == 10 and s["steps_per_sec"] > 0


def test_profiler_trace_noop_and_real(tmp_path):
    from coda_tpu.utils.profiling import trace

    with trace(None):  # no-op path
        pass
    d = str(tmp_path / "prof")
    import jax
    import jax.numpy as jnp

    with trace(d):
        jax.jit(lambda x: x * 2)(jnp.ones(8)).block_until_ready()
    # jax.profiler writes a plugins/profile tree under the log dir
    assert any("profile" in r for r, _, _ in os.walk(d))


def test_artifact_logging(tmp_path):
    from coda_tpu.tracking import TrackingStore
    from coda_tpu.utils.viz import plot_bar

    db = str(tmp_path / "t.sqlite")
    store = TrackingStore(db)
    with store.run("exp", "run-a") as r:
        p1 = r.log_artifact_bytes("blob.bin", b"\x00\x01")
        p2 = r.log_figure("chart", plot_bar([1.0, 2.0]))
        uuid = r.run_uuid
    assert os.path.exists(p1) and os.path.exists(p2)
    assert p2.endswith(".png")
    (uri,) = store.query(
        "SELECT artifact_uri FROM runs WHERE run_uuid=?", (uuid,)
    )[0]
    assert uri and os.path.isdir(uri)
    store.close()


def test_distributed_single_process_noop(monkeypatch):
    from coda_tpu.parallel import distributed

    monkeypatch.delenv("JAX_COORDINATOR", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert distributed.initialize() is False
    assert distributed.is_primary() is True


def test_cli_debug_viz_and_profile(tmp_path):
    from coda_tpu.cli import main

    db = str(tmp_path / "v.sqlite")
    prof = str(tmp_path / "prof")
    main([
        "--synthetic", "4,48,3", "--method", "coda", "--iters", "5",
        "--seeds", "1", "--platform", "cpu", "--tracking-db", db,
        "--debug-viz", "--profile-dir", prof,
    ])
    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(db)
    rows = store.query(
        "SELECT artifact_uri FROM runs WHERE artifact_uri IS NOT NULL"
    )
    assert rows, "debug-viz should have logged artifacts"
    arts = os.listdir(rows[0][0])
    assert "regret_curve.png" in arts and "pbest.png" in arts
    store.close()
    assert os.path.isdir(prof)
