import numpy as np

from coda_tpu.tracking import TrackingStore


def test_store_schema_and_hierarchy(tmp_path):
    db = str(tmp_path / "test.sqlite")
    store = TrackingStore(db)
    with store.run("taskA", "taskA-coda", params={"method": "coda"}) as parent:
        with store.run("taskA", "taskA-coda-0", parent=parent,
                       params={"seed": 0}) as child:
            child.log_metric_series("regret", [0.5, 0.3, 0.1], start_step=1)
            child.log_metric_series("cumulative regret", [0.5, 0.8, 0.9],
                                    start_step=1)
    # finished statuses
    assert store.is_finished("taskA", "taskA-coda")
    assert store.is_finished("taskA", "taskA-coda-0")
    assert not store.is_finished("taskA", "nope")

    # child lookup via the parentRunId tag
    parent_uuid = store.find_run("taskA", "taskA-coda")[0]
    children = store.child_runs(parent_uuid)
    assert len(children) == 1
    series = store.metric_series(children[0], "regret")
    assert series == [(1, 0.5), (2, 0.3), (3, 0.1)]
    store.close()


def test_reference_analysis_sql_runs_unchanged(tmp_path):
    """The exact join shape of the reference's paper SQL must work."""
    db = str(tmp_path / "coda.sqlite")
    store = TrackingStore(db)
    for seed, final in [(0, 1.25), (1, 0.75)]:
        with store.run("cifar10_5592", "cifar10_5592-coda") as parent:
            with store.run("cifar10_5592", f"cifar10_5592-coda-{seed}",
                           parent=parent) as child:
                child.log_metric_series(
                    "cumulative regret",
                    np.linspace(0.0, final, 100), start_step=1,
                )
    rows = store.query(
        """
        SELECT  e.name AS task, rn.value AS run_name, m.value, m.step
        FROM    metrics m
        JOIN    runs r ON m.run_uuid = r.run_uuid
        JOIN    experiments e ON r.experiment_id = e.experiment_id
        JOIN    tags t_parent
               ON r.run_uuid = t_parent.run_uuid
              AND t_parent.key = 'mlflow.parentRunId'
        LEFT JOIN tags rn
               ON r.run_uuid = rn.run_uuid
              AND rn.key = 'mlflow.runName'
        WHERE   m.key = 'cumulative regret'
          AND   m.step = 100
          AND   r.lifecycle_stage = 'active'
          AND   e.lifecycle_stage = 'active'
        """
    )
    assert len(rows) == 2
    tasks = {r[0] for r in rows}
    names = {r[1] for r in rows}
    assert tasks == {"cifar10_5592"}
    assert names == {"cifar10_5592-coda-0", "cifar10_5592-coda-1"}
    vals = sorted(r[2] for r in rows)
    assert vals[0] == 0.75 and vals[1] == 1.25
    store.close()


def test_resume_skips_finished(tmp_path):
    db = str(tmp_path / "r.sqlite")
    store = TrackingStore(db)
    with store.run("t", "t-iid-0") as r:
        r.log_metric("regret", 0.1, step=1)
    assert store.is_finished("t", "t-iid-0")
    # reopening reuses the same run_uuid
    first = store.find_run("t", "t-iid-0")[0]
    with store.run("t", "t-iid-0") as r2:
        assert r2.run_uuid == first
    store.close()


def test_failed_status(tmp_path):
    store = TrackingStore(str(tmp_path / "f.sqlite"))
    try:
        with store.run("t", "t-x-0"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    found = store.find_run("t", "t-x-0")
    assert found[1] == "FAILED"
    assert not store.is_finished("t", "t-x-0")
    store.close()


def test_nan_metric_stored_as_is_nan(tmp_path):
    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(str(tmp_path / "db.sqlite"))
    with store.run("exp", "run") as r:
        r.log_metric_series("m", [1.0, float("nan"), 3.0])
    rows = store.query(
        "SELECT value, is_nan FROM metrics ORDER BY step")
    assert rows == [(1.0, 0), (0.0, 1), (3.0, 0)]


def test_relog_series_replaces_not_duplicates(tmp_path):
    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(str(tmp_path / "db.sqlite"))
    with store.run("exp", "run") as r:
        r.log_metric_series("m", [1.0, 2.0])
        uuid = r.run_uuid
    # reuse the run (e.g. --force-rerun) and re-log
    with store.run("exp", "run") as r2:
        assert r2.run_uuid == uuid
        r2.log_metric_series("m", [5.0, 6.0])
    assert store.metric_series(uuid, "m") == [(1, 5.0), (2, 6.0)]


def test_metric_series_reconstitutes_nan(tmp_path):
    import math

    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(str(tmp_path / "db.sqlite"))
    with store.run("exp", "run") as r:
        r.log_metric_series("m", [1.0, float("nan")])
        uuid = r.run_uuid
    series = store.metric_series(uuid, "m")
    assert series[0] == (1, 1.0)
    assert series[1][0] == 2 and math.isnan(series[1][1])


def test_latest_metrics_maintained(tmp_path):
    """latest_metrics (the MLflow UI's run-table source) holds the max-step
    row per key and follows re-logs."""
    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(str(tmp_path / "db.sqlite"))
    with store.run("exp", "run") as r:
        r.log_metric_series("regret", [0.5, 0.3, 0.1], start_step=1)
        r.log_metric("final", 7.0, step=0)
        uuid = r.run_uuid
    rows = dict(
        (k, (v, s)) for k, v, s in store.query(
            "SELECT key, value, step FROM latest_metrics WHERE run_uuid=?",
            (uuid,))
    )
    assert rows["regret"] == (0.1, 3)
    assert rows["final"] == (7.0, 0)
    # re-log replaces
    with store.run("exp", "run") as r2:
        r2.log_metric_series("regret", [0.4, 0.2, 0.05], start_step=1)
    (v, s), = store.query(
        "SELECT value, step FROM latest_metrics WHERE run_uuid=? AND"
        " key='regret'", (uuid,))
    assert (v, s) == (0.05, 3)
