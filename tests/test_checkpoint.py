"""Checkpoint/resume: resumed runs must be identical to uninterrupted ones.

The reference has no intra-run checkpointing (resume granularity is the whole
seed-run via MLflow status, reference ``main.py:155-157``); this subsystem is
new capability, so the tests define its contract: (a) chunked+checkpointed
execution equals the single-scan result, (b) killing a run mid-way and
resuming from disk completes with identical traces, (c) old checkpoints are
garbage-collected.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from coda_tpu.engine import (
    latest_step,
    run_experiment,
    run_experiment_resumable,
)
from coda_tpu.engine.checkpoint import ExperimentCheckpointer
from coda_tpu.oracle import true_losses
from coda_tpu.selectors import CODAHyperparams, make_coda, make_iid


@pytest.fixture(scope="module")
def setup(tiny_task):
    losses = true_losses(tiny_task.preds, tiny_task.labels)
    return tiny_task, losses


def _assert_results_equal(a, b):
    """Selection decisions (indices, best-model, flags) must be EXACT; float
    metrics may differ by ~1 ulp because the chunked runner and the single
    scan are separately compiled programs — XLA may schedule a reduction
    (e.g. the incremental pi-hat column einsum) differently per scan length,
    which is not a resume error."""
    exact = ("chosen_idx", "true_class", "best_model", "stochastic")
    for name in a._fields:
        x = np.asarray(getattr(a, name))
        y = np.asarray(getattr(b, name))
        if name in exact:
            np.testing.assert_array_equal(x, y, err_msg=name)
        else:
            np.testing.assert_allclose(x, y, rtol=3e-5, atol=1e-7,
                                       err_msg=name)


def test_resumable_matches_single_scan(setup, tmp_path):
    task, losses = setup
    sel = make_coda(task.preds, CODAHyperparams(eig_chunk=16))
    want = run_experiment(sel, task, iters=12, seed=3, model_losses=losses)
    got = run_experiment_resumable(
        sel, task.labels, losses, iters=12, seed=3,
        ckpt_dir=str(tmp_path / "ck"), every=5,
    )
    _assert_results_equal(want, got)


def test_resume_after_interrupt(setup, tmp_path):
    task, losses = setup
    sel = make_iid(task.preds)
    ckpt = str(tmp_path / "ck")

    # run the first 10 of 20 rounds, then "crash"
    run_experiment_resumable(sel, task.labels, losses, iters=10, seed=0,
                             ckpt_dir=ckpt, every=5)
    assert latest_step(ckpt) == 5  # final chunk of a run isn't checkpointed

    # a fresh process resumes from round 5 and completes all 20
    resumed = run_experiment_resumable(sel, task.labels, losses, iters=20,
                                       seed=0, ckpt_dir=ckpt, every=5)
    fresh = run_experiment(sel, task, iters=20, seed=0, model_losses=losses)
    _assert_results_equal(fresh, resumed)


def test_checkpoint_gc(tmp_path):
    ck = ExperimentCheckpointer(str(tmp_path / "ck"), keep=2)
    for r in (5, 10, 15, 20):
        ck.save(r, {"x": jnp.arange(3), "r": np.int32(r)})
    kept = sorted(os.listdir(str(tmp_path / "ck")))
    assert kept == ["step_15", "step_20"]
    assert latest_step(str(tmp_path / "ck")) == 20
    assert int(ck.restore(20)["r"]) == 20


def test_latest_step_empty(tmp_path):
    assert latest_step(str(tmp_path / "nope")) is None


def test_resume_with_smaller_iters(setup, tmp_path):
    """Round keys are prefix-stable, so a shorter rerun restores an earlier
    checkpoint (≤ iters) and still matches a fresh short run exactly."""
    task, losses = setup
    sel = make_iid(task.preds)
    ckpt = str(tmp_path / "ck")
    run_experiment_resumable(sel, task.labels, losses, iters=20, seed=0,
                             ckpt_dir=ckpt, every=5)  # leaves step_5..15
    short = run_experiment_resumable(sel, task.labels, losses, iters=12,
                                     seed=0, ckpt_dir=ckpt, every=5)
    fresh = run_experiment(sel, task, iters=12, seed=0, model_losses=losses)
    _assert_results_equal(fresh, short)


def test_fingerprint_mismatch_raises(setup, tmp_path):
    task, losses = setup
    ckpt = str(tmp_path / "ck")
    sel_a = make_coda(task.preds, CODAHyperparams(alpha=0.9, eig_chunk=16))
    run_experiment_resumable(sel_a, task.labels, losses, iters=6, seed=0,
                             ckpt_dir=ckpt, every=3)
    sel_b = make_coda(task.preds, CODAHyperparams(alpha=0.5, eig_chunk=16))
    with pytest.raises(ValueError, match="different configuration"):
        run_experiment_resumable(sel_b, task.labels, losses, iters=6, seed=0,
                                 ckpt_dir=ckpt, every=3)


def test_fingerprint_tolerates_new_default_field(setup, tmp_path):
    """A checkpoint written before a hyperparam existed keeps resuming while
    the new field is at its default — but an explicit override is rejected."""
    import json

    task, losses = setup
    sel = make_coda(task.preds, CODAHyperparams(eig_chunk=16))
    ckpt = str(tmp_path / "ck")
    run_experiment_resumable(sel, task.labels, losses, iters=6, seed=0,
                             ckpt_dir=ckpt, every=3)

    # simulate a checkpoint from before eig_mode existed
    fp_path = os.path.join(ckpt, "fingerprint.json")
    with open(fp_path) as f:
        saved = json.load(f)
    del saved["hyperparams"]["eig_mode"]
    with open(fp_path, "w") as f:
        json.dump(saved, f)

    # default value of the new field: resume is fine
    run_experiment_resumable(sel, task.labels, losses, iters=6, seed=0,
                             ckpt_dir=ckpt, every=3)

    # explicit non-default override of the new field: real mismatch
    sel_direct = make_coda(task.preds,
                           CODAHyperparams(eig_chunk=16, eig_mode="direct"))
    with pytest.raises(ValueError, match="different configuration"):
        run_experiment_resumable(sel_direct, task.labels, losses, iters=6,
                                 seed=0, ckpt_dir=ckpt, every=3)


def test_budget_guard(setup, tmp_path):
    from coda_tpu.selectors import make_activetesting

    task, losses = setup
    sel = make_activetesting(task.preds, budget=5)
    with pytest.raises(ValueError, match="fixed label buffer"):
        run_experiment_resumable(sel, task.labels, losses, iters=10, seed=0,
                                 ckpt_dir=str(tmp_path / "ck"), every=5)


def test_stale_state_layout_fails_loudly(setup, tmp_path):
    """A checkpoint whose state pytree predates a selector-state layout
    change (fewer leaves) must fail with the actionable message, not a raw
    tree-unflatten error."""
    import shutil

    task, losses = setup
    sel = make_coda(task.preds, CODAHyperparams(eig_chunk=16))
    ckpt = str(tmp_path / "ck")
    run_experiment_resumable(sel, task.labels, losses, iters=9, seed=0,
                             ckpt_dir=ckpt, every=3)
    # simulate an old layout: drop one saved state leaf from the newest step
    step = latest_step(ckpt)
    ckptr = ExperimentCheckpointer(ckpt)
    tree = ckptr.restore(step)
    n = len(tree["state"])
    tree["state"] = {f"{i:04d}": tree["state"][f"{i:04d}"]
                     for i in range(n - 1)}
    shutil.rmtree(os.path.join(ckpt, f"step_{step}"))
    ckptr.save(step, tree)
    with pytest.raises(ValueError, match="layout change"):
        run_experiment_resumable(sel, task.labels, losses, iters=12, seed=0,
                                 ckpt_dir=ckpt, every=3)


def test_resumable_bf16_cache_roundtrips(setup, tmp_path):
    """The bf16 EIG cache must survive the orbax snapshot/restore cycle:
    a resumed run equals the single-scan run with eig_cache_dtype set."""
    task, losses = setup
    sel = make_coda(task.preds, CODAHyperparams(
        eig_chunk=16, eig_mode="incremental", eig_cache_dtype="bfloat16"))
    want = run_experiment(sel, task, iters=10, seed=3, model_losses=losses)
    got = run_experiment_resumable(
        sel, task.labels, losses, iters=10, seed=3,
        ckpt_dir=str(tmp_path / "ck16"), every=4,
    )
    _assert_results_equal(want, got)
