"""The DMA-gather kernel behind the delta pi-hat refresh must match the
XLA take-along-axis path bitwise-closely (interpret mode on CPU; Mosaic on
real TPUs), fall back under vmap, and respect its VMEM tile cap. The
kernel consumes the flat (C·H, 1, Np) layout of prep_gather_layout —
Mosaic cannot slice single sublane rows out of the tiled (C, H, N)
buffer."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _case(key, C, H, N):
    k1, k2 = jax.random.split(key)
    src = jax.random.normal(k1, (C, H, N), jnp.float32)
    s = jax.random.randint(k2, (H,), 0, C, jnp.int32)
    return src, s


def _prepped(src):
    from coda_tpu.ops.pallas_gather import prep_gather_layout

    return prep_gather_layout(src)


def test_gather_matches_xla_path():
    from coda_tpu.ops.pallas_gather import (
        gather_rows_sum_prepped,
        gather_rows_sum_xla,
    )

    for seed, (C, H, N) in enumerate([(4, 12, 256), (10, 37, 1000),
                                      (3, 8, 129)]):
        src, s = _case(jax.random.PRNGKey(seed), C, H, N)
        ref = np.asarray(gather_rows_sum_xla(src, s))
        out = np.asarray(gather_rows_sum_prepped(_prepped(src), s, N,
                                                 interpret=True))
        assert out.shape == (N,)
        # same adds, sequential-vs-tree order only
        np.testing.assert_allclose(ref, out, rtol=1e-6, atol=1e-6)


def test_prep_gather_layout_shape():
    from coda_tpu.ops.pallas_gather import prep_gather_layout

    src, _ = _case(jax.random.PRNGKey(7), 3, 5, 129)
    flat = prep_gather_layout(src)
    assert flat.shape == (15, 1, 256)
    # row (c, h) lands at flat index c*H + h with the tail zero-padded
    np.testing.assert_array_equal(np.asarray(flat[2 * 5 + 3, 0, :129]),
                                  np.asarray(src[2, 3]))
    assert float(jnp.abs(flat[:, :, 129:]).max()) == 0.0


def test_gather_vmap_falls_back_to_xla():
    from coda_tpu.ops.pallas_gather import (
        gather_rows_sum_prepped,
        gather_rows_sum_xla,
    )

    keys = jax.random.split(jax.random.PRNGKey(9), 3)
    cases = [_case(k, 4, 10, 64) for k in keys]
    flats = jnp.stack([_prepped(src) for src, _ in cases])
    srcs = jnp.stack([src for src, _ in cases])
    ss = jnp.stack([s for _, s in cases])
    out = jax.vmap(
        lambda f, s: gather_rows_sum_prepped(f, s, 64, interpret=True)
    )(flats, ss)
    ref = jax.vmap(gather_rows_sum_xla)(srcs, ss)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_resolve_pi_update_tile_cap_and_explicit():
    """auto -> delta on CPU regardless of N; explicit values pass through;
    the N tile-cap argument only bites on TPU backends (this suite is
    CPU-pinned, so assert the CPU half and the pass-throughs)."""
    from coda_tpu.selectors import CODAHyperparams
    from coda_tpu.selectors.coda import resolve_pi_update

    assert resolve_pi_update(CODAHyperparams()) == "delta"
    assert resolve_pi_update(CODAHyperparams(), 10**9) == "delta"
    assert resolve_pi_update(CODAHyperparams(pi_update="exact")) == "exact"
    assert resolve_pi_update(CODAHyperparams(pi_update="delta"), 10**9) == \
        "delta"


def test_delta_update_with_pallas_gather_matches_default():
    """update_pi_hat_column_delta with the kernel gather must reproduce
    the default-path posteriors on a real update step."""
    from coda_tpu.ops.pallas_gather import gather_rows_sum_prepped
    from coda_tpu.selectors.coda import update_pi_hat_column_delta

    key = jax.random.PRNGKey(5)
    C, H, N = 5, 9, 200
    preds = jax.nn.softmax(jax.random.normal(key, (H, N, C)), axis=-1)
    pbc = jnp.transpose(preds, (2, 0, 1))
    unnorm = jnp.abs(jax.random.normal(jax.random.PRNGKey(6), (N, C))) + 0.1
    s = preds[:, 17, :].argmax(-1).astype(jnp.int32)

    ref = update_pi_hat_column_delta(jnp.int32(2), s, pbc, unnorm, 0.01)
    out = update_pi_hat_column_delta(
        jnp.int32(2), s, _prepped(pbc), unnorm, 0.01,
        gather_fn=lambda f, sc: gather_rows_sum_prepped(f, sc, N,
                                                        interpret=True))
    for r, o in zip(ref, out):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o),
                                   rtol=1e-6, atol=1e-7)
