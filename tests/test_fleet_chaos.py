"""Fleet chaos hardening tests (ISSUE 14): epoch-fenced ownership, the
migration journal, and the hardened replica transport.

The load-bearing claims: (1) the split-brain double-apply is
STRUCTURALLY impossible — the exact interleaving (partition → migrate →
heal → old-owner label retry) ends in a typed ``StaleOwner`` fencing
rejection plus a single commit on the new owner, pinned as a regression
test; (2) a SIGKILL mid-migration at ANY journal phase resolves on
restart to didn't-move or moved-exactly-once, never gone or doubled;
(3) the transport's breaker walks trip → half-open → recovery and a
retry-budget exhaustion degrades to a typed retryable 503 in bounded
time, never a hang; (4) ownership epochs survive demote/wake round trips
unchanged (only a committed move bumps them) and the new observability
families render lint-clean.
"""

from __future__ import annotations

import os
import time
import uuid

import pytest

H, N, C = 4, 48, 4


@pytest.fixture(scope="module")
def task():
    from coda_tpu.data import make_synthetic_task

    return make_synthetic_task(seed=0, H=H, N=N, C=C)


def _fleet(task, tmp, n=2, fault_spec=None, capacity=4, hysteresis=2):
    from coda_tpu.serve import Fleet, SelectorSpec, ServeApp
    from coda_tpu.telemetry import SessionRecorder

    def factory(rid):
        app = ServeApp(capacity=capacity, max_wait=0.001,
                       spec=SelectorSpec.create("coda",
                                                n_parallel=capacity),
                       recorder=SessionRecorder(
                           out_dir=os.path.join(tmp, rid)))
        app.add_task(task.name, task.preds)
        return app

    fleet = Fleet(factory, n_replicas=n,
                  journal_path=os.path.join(tmp, "router_migrations.log"),
                  fault_spec=fault_spec, health_hysteresis=hysteresis)
    for h in fleet.router.replicas.values():
        h.transport.backoff_s = 0.005
        h.transport.breaker.cooldown_s = 0.05
    return fleet.start(warm=True)


# ---------------------------------------------------------------------------
# the acceptance regression: partition -> migrate -> heal -> old-owner
# label retry => fencing rejection + exactly one commit
# ---------------------------------------------------------------------------

def test_stale_owner_fence_regression(task, tmp_path):
    """The split-brain interleaving, forced exactly: a migration whose
    source fence is eaten by a partition leaves a stale copy behind;
    after the heal (and the source losing its in-memory hold, as a
    restart would), a label retried AT the stale copy with the router's
    epoch stamp MUST be refused typed — and the router-mediated retry
    commits exactly once on the new owner."""
    from coda_tpu.serve import StaleOwner

    # every fence call on every edge is dropped: the partition window
    # swallows the migration's commit-fence (retries included)
    fleet = _fleet(task, str(tmp_path),
                   fault_spec="net_drop:task=fence,times=8")
    r = fleet.router
    try:
        out = r.open_session(seed=0)
        sid = out["session"]
        out = r.label(sid, int(out["idx"]) % C,
                      request_id=uuid.uuid4().hex)
        src = r._locate(sid)
        dst = [x for x in fleet.replica_ids if x != src][0]
        info = r.migrate_session(sid, src, dst)
        assert info.get("migrated") == sid, info
        assert info["via"] in ("snapshot", "replay")
        assert info.get("fence_pending"), \
            "the injected partition should have eaten the fence"
        assert r.counters["fence_failures"] == 1
        assert info["epoch"] == 1 and r._epochs[sid] == 1
        # the destination's copy carries the bumped epoch
        assert fleet.apps[dst].store.get(sid).epoch == 1
        # partition heals; the source "restarts", losing its in-memory
        # hold — the stale copy is revivable again
        src_app = fleet.apps[src]
        with src_app.store.lock:
            src_app._holds.clear()
        # the old-owner write attempt: a label carried to the stale copy
        # with the router's stamp — refused, typed, nothing committed
        with pytest.raises(StaleOwner):
            r.replicas[src].label(sid, 0, request_id=uuid.uuid4().hex,
                                  epoch=r._epochs[sid])
        assert src_app.metrics.snapshot()["fencing_rejections"] == 1
        assert src_app.store.get(sid).n_labeled == 1  # nothing committed
        # the same logical label through the router: re-located to the
        # new owner, committed exactly once
        out = r.label(sid, int(out["idx"]) % C,
                      request_id=uuid.uuid4().hex)
        assert out["n_labeled"] == 2
        assert fleet.apps[dst].store.get(sid).n_labeled == 2
        # a router-routed verb that LANDS on the stale copy re-routes
        # transparently (the _forward StaleOwner path): force the stale
        # location and label again
        with r._lock:
            r._placed[sid] = src
        out = r.label(sid, int(out["idx"]) % C,
                      request_id=uuid.uuid4().hex)
        assert out["n_labeled"] == 3
        assert r.counters["fencing_rejections"] >= 1
        assert r.counters["reroutes"] >= 1
        assert fleet.apps[dst].store.get(sid).n_labeled == 3
    finally:
        fleet.drain(timeout=10)


# ---------------------------------------------------------------------------
# SIGKILL mid-migration at each journal phase -> restore or finalize
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("phase", ["intent", "exported", "imported"])
def test_journal_recovery_per_phase(task, tmp_path, phase):
    """The router dies between migration steps; a fresh router over the
    same replicas + journal resolves the in-doubt move: didn't-move for
    intent/exported (the source's hold lifts, its copy serves), moved-
    exactly-once for imported (the source is fenced, the epoch adopted)."""
    from coda_tpu.serve import InprocReplica, SessionRouter
    from coda_tpu.serve.journal import payload_digest

    fleet = _fleet(task, str(tmp_path))
    r = fleet.router
    r2 = None
    try:
        out = r.open_session(seed=0)
        sid = out["session"]
        out = r.label(sid, int(out["idx"]) % C,
                      request_id=uuid.uuid4().hex)
        src = r._locate(sid)
        dst = [x for x in fleet.replica_ids if x != src][0]
        epoch_next = 1
        mid = r.journal.begin(sid, src, dst, epoch_next)
        if phase in ("exported", "imported"):
            payload = dict(r.replicas[src].export_for_migration(sid),
                           epoch=epoch_next)
            r.journal.record(mid, "exported",
                             digest=payload_digest(payload),
                             n_labeled=payload.get("n_labeled"))
            assert fleet.apps[src].held(sid)
        if phase == "imported":
            r.replicas[dst].import_payload(payload)
            r.journal.record(mid, "imported")
        r.stop()  # the router is "SIGKILLed" here: gate + epoch map die
        r2 = SessionRouter(
            {rid: InprocReplica(rid, app)
             for rid, app in fleet.apps.items()},
            journal_path=str(tmp_path / "router_migrations.log"))
        rep = r2.recover_from_journal()
        assert rep["resolved"] == 1
        if phase == "imported":
            assert rep["finalized"] == [sid]
            assert r2._epochs[sid] == epoch_next
            assert fleet.apps[dst].store.alive(sid)
            # the source copy is GONE — no second authority
            assert not fleet.apps[src].store.alive(sid)
            assert not fleet.apps[src].tiers.parked(sid)
        else:
            assert rep["restored"] == [sid]
            assert not fleet.apps[src].held(sid)  # the hold lifted
        # the client's next label commits exactly once either way
        out = r2.label(sid, int(out["idx"]) % C,
                       request_id=uuid.uuid4().hex)
        assert out["n_labeled"] == 2
        assert r2.counters["journal_replays"] == 1
    finally:
        if r2 is not None:
            r2.drain()
        fleet.drain(timeout=10)


def test_journal_torn_tail_and_fold(tmp_path):
    """The journal's framing contract: a torn final line (SIGKILL
    mid-append) is dropped, earlier records fold per-mid with the last
    phase winning, and committed() is the durable epoch map."""
    from coda_tpu.serve.journal import MigrationJournal

    p = str(tmp_path / "j.log")
    j = MigrationJournal(p)
    m1 = j.begin("aaaa", "r0", "r1", 1)
    j.record(m1, "exported", digest="d1", n_labeled=3)
    j.record(m1, "imported")
    j.record(m1, "committed", epoch=1, fenced=True)
    m2 = j.begin("bbbb", "r1", "r0", 4)
    j.record(m2, "exported", digest="d2", n_labeled=7)
    j.close()
    with open(p, "a") as f:
        f.write('{"mid": "cccc#9", "phase": "int')  # torn tail
    j2 = MigrationJournal(p)
    assert j2.torn_tail_dropped
    doubt = j2.in_doubt()
    assert [d["sid"] for d in doubt] == ["bbbb"]
    assert doubt[0]["phase"] == "exported"
    assert doubt[0]["digest"] == "d2"
    assert j2.committed() == {"aaaa": {"epoch": 1, "dst": "r1"}}
    # new mids never collide with replayed ones
    m3 = j2.begin("dddd", "r0", "r1", 1)
    assert m3.split("#")[1] not in {m1.split("#")[1], m2.split("#")[1]}
    j2.close()


# ---------------------------------------------------------------------------
# epochs survive demote/wake round trips; only a committed move bumps
# ---------------------------------------------------------------------------

def test_epoch_preserved_through_demote_wake_and_stream(task, tmp_path):
    """A demote/wake round trip must NOT advance the ownership epoch (a
    wake is a page-in, not an ownership change) — and the epoch rides
    the stream meta so a crash-restored copy keeps it."""
    fleet = _fleet(task, str(tmp_path))
    r = fleet.router
    try:
        out = r.open_session(seed=0)
        sid = out["session"]
        out = r.label(sid, int(out["idx"]) % C)
        src = r._locate(sid)
        dst = [x for x in fleet.replica_ids if x != src][0]
        assert r.migrate_session(sid, src, dst).get("migrated") == sid
        app = fleet.apps[dst]
        assert app.store.get(sid).epoch == 1
        # demote -> payload keeps epoch 1 -> wake restores epoch 1
        assert app.tiers.try_demote(sid)
        assert int(app.tiers.parked_payload(sid)["epoch"]) == 1
        out = r.label(sid, int(out["idx"]) % C)   # transparent wake
        assert out["n_labeled"] == 2
        assert app.store.get(sid).epoch == 1      # unchanged
        # the stream meta carries it for crash restore: the destination's
        # stream file was written by import_history with the bumped epoch
        from coda_tpu.serve.recovery import load_session_stream

        meta, _, _ = load_session_stream(
            os.path.join(str(tmp_path), dst, f"session_{sid}.jsonl"))
        assert int(meta.get("epoch") or 0) == 1
        # ...while the SOURCE's fenced stream (sealed, pre-migration)
        # still reads epoch 0 — a crash restore of it yields a copy the
        # fence rejects, not a second authority
        meta_src, _, closed = load_session_stream(
            os.path.join(str(tmp_path), src, f"session_{sid}.jsonl"))
        assert closed and int(meta_src.get("epoch") or 0) == 0
    finally:
        fleet.drain(timeout=10)


# ---------------------------------------------------------------------------
# transport: breaker transitions, retry budget, typed fast-fail
# ---------------------------------------------------------------------------

def test_breaker_trip_half_open_recovery():
    from coda_tpu.serve.transport import CircuitBreaker

    b = CircuitBreaker(threshold=3, cooldown_s=0.05)
    assert b.state == "closed"
    for _ in range(3):
        assert b.allow()
        b.record_failure()
    assert b.state == "open" and b.trips == 1
    assert not b.allow()                  # fail fast while open
    time.sleep(0.06)
    assert b.state == "half_open"
    assert b.allow()                      # exactly one probe
    assert not b.allow()                  # ...everyone else waits
    b.record_failure()                    # failed probe: re-open
    assert b.state == "open" and b.trips == 2
    time.sleep(0.06)
    assert b.allow()
    b.record_success()                    # recovered
    assert b.state == "closed"
    assert b.consecutive_failures == 0


def test_transport_retries_only_idempotent_verbs():
    """A timed-out label WITHOUT a request_id must not retry (it could
    double-apply); with one it retries; reads always retry."""
    from coda_tpu.serve.transport import ReplicaTransport

    calls = {"n": 0}

    def flaky(deadline):
        calls["n"] += 1
        if calls["n"] == 1:
            raise TimeoutError("deadline")
        return {"ok": True}

    t = ReplicaTransport("r0", max_retries=2, backoff_s=0.001)
    calls["n"] = 0
    with pytest.raises(TimeoutError):
        t.call("label", flaky, idempotent=False)   # no request_id
    assert calls["n"] == 1                          # never retried
    calls["n"] = 0
    assert t.call("label", flaky, idempotent=True)["ok"]  # dedupe-gated
    assert calls["n"] == 2
    calls["n"] = 0
    assert t.call("best", flaky)["ok"]              # reads always
    assert t.retries_total == 2
    assert t.retries_by_verb == {"label": 1, "best": 1}


def test_retry_budget_exhaustion_is_typed_503_not_hang():
    """A black-holed replica burns the budget once, then fails FAST with
    the typed retryable error the front door maps to 503 — bounded time,
    bounded call amplification, never a hang."""
    from coda_tpu.serve.state import SlabFull
    from coda_tpu.serve.transport import ReplicaTransport, \
        ReplicaUnavailable

    t = ReplicaTransport("r0", max_retries=3, backoff_s=0.001,
                         breaker_threshold=10_000, retry_budget=4)

    def dead(deadline):
        raise ConnectionRefusedError("refused")

    t0 = time.perf_counter()
    outcomes = []
    for _ in range(10):
        try:
            t.call("best", dead)
        except ReplicaUnavailable:
            outcomes.append("unavailable")
        except ConnectionRefusedError:
            outcomes.append("refused")
    assert time.perf_counter() - t0 < 2.0          # bounded, no hang
    assert "unavailable" in outcomes               # the typed fast-fail
    assert t.budget.exhaustions > 0
    # ReplicaUnavailable IS a SlabFull: the HTTP envelope answers 503
    assert issubclass(ReplicaUnavailable, SlabFull)
    # the budget refills on success: service recovers organically
    t.call("best", lambda d: {"ok": True})
    assert t.budget.tokens > 0


def test_breaker_drives_router_eviction_distinct_from_health(task,
                                                             tmp_path):
    """A tripped breaker evicts the replica with status ``breaker_open``
    — reported distinctly from health eviction on /stats — and the
    half-open probe via the health poll re-admits it after recovery."""
    fleet = _fleet(task, str(tmp_path))
    r = fleet.router
    try:
        h = r.replicas["r0"]
        for _ in range(h.transport.breaker.threshold):
            h.transport.breaker.record_failure()
        assert h.transport.breaker.state == "open"
        statuses = r.check_health()
        assert statuses["r0"] == "breaker_open"
        assert "r0" not in r.routable()
        st = r.stats()["router"]
        assert st["breakers"]["r0"]["state"] in ("open", "half_open")
        assert st["health"]["r0"] == "breaker_open"
        # cooldown passes; the next polls are the half-open probe and
        # the hysteresis confirmation — recovery rejoins
        time.sleep(0.06)
        r.check_health()
        r.check_health()
        assert "r0" in r.routable()
        assert r.stats()["router"]["breakers"]["r0"]["state"] == "closed"
    finally:
        fleet.drain(timeout=10)


# ---------------------------------------------------------------------------
# observability: the new families render, lint-clean
# ---------------------------------------------------------------------------

def test_chaos_metrics_families_lint_clean(task, tmp_path):
    from coda_tpu.telemetry.prometheus import lint

    fleet = _fleet(task, str(tmp_path),
                   fault_spec="net_drop:after=2,times=2,task=label")
    r = fleet.router
    try:
        out = r.open_session(seed=0)
        sid = out["session"]
        for _ in range(4):
            out = r.label(sid, int(out["idx"]) % C,
                          request_id=uuid.uuid4().hex)
        text = r.render_metrics()
        assert lint(text) == []
        assert "coda_replica_breaker_state{" in text
        assert "coda_transport_retries_total{" in text
        assert "coda_fencing_rejections_total" in text
        assert "coda_migration_journal_replays_total" in text
        st = r.stats()["router"]
        assert st["journal"]["moves"] == 0
        assert sum(st["transport_retries"].values()) >= 1  # drops absorbed
        assert out["n_labeled"] == 4                       # exactly-once
    finally:
        fleet.drain(timeout=10)
