"""The sparse top-K class-row posterior tier + the amortized P(best) rung.

Contract under test (ISSUE 9):

  * ``sparse:K>=C`` (the untruncated parity layout) is BITWISE equal to
    the dense posterior on a real-digits trace — scores, picks, best
    models;
  * ``sparse:K<C`` conserves row mass exactly, so the Beta reduction the
    EIG quadrature consumes stays within float summation order of dense:
    the selection trace holds the documented 2.34e-4 score contract and
    any divergence arrives CLASSIFIED by the replay triage (near-tie
    flip), never as an unexplained score delta;
  * the auto ``eig_mode`` budget charges the posterior representation:
    at the ImageNet pool shape (C=1000) dense and sparse both stay
    incremental, and at pool shapes where the dense (H, C, C) carry blows
    the budget the sparse representation is exactly what keeps the
    incremental tier viable — pinned both ways so budget edits can't
    silently flip the C=1000 tier;
  * ``eig_pbest='amortized'`` engages the closed-form logistic-normal
    tables ONLY above the committed concentration gate
    (``_AMORTIZED_MIN_CONC``): below it the trace is bitwise the
    quadrature's, above it scores stay within the 2.34e-4 contract;
  * the ``posterior`` / ``eig_pbest`` knobs are fingerprinted, so
    ``cli replay --against`` auto-tolerance compares dense-vs-sparse
    under the score contract instead of reporting a fake bitwise
    divergence.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

_DIGITS = os.path.join(os.path.dirname(__file__), "..", "data",
                       "digits.npz")


def _rand_dirichlets(key, H, C):
    return jax.random.uniform(key, (H, C, C), minval=0.05, maxval=3.0)


# ---------------------------------------------------------------------------
# representation primitives
# ---------------------------------------------------------------------------

def test_parse_posterior():
    from coda_tpu.ops.sparse_rows import parse_posterior

    assert parse_posterior("dense") is None
    assert parse_posterior("sparse:32") == 32
    for bad in ("Sparse:32", "sparse:0", "sparse:-1", "sparse:x",
                "sparse", "topk:4"):
        with pytest.raises(ValueError, match="unknown posterior"):
            parse_posterior(bad)


def test_sparsify_conserves_row_mass_and_beta():
    """Truncation folds untracked mass into the residual, so the Beta
    reduction (diagonal + total off-diagonal mass) matches the dense one
    to summation-order float error; the full layout matches bitwise."""
    from coda_tpu.ops.beta import dirichlet_to_beta
    from coda_tpu.ops.sparse_rows import sparsify, to_beta

    H, C = 6, 12
    d = _rand_dirichlets(jax.random.PRNGKey(0), H, C)
    a_ref, b_ref = dirichlet_to_beta(d)

    s_full = sparsify(d, C)
    a, b = to_beta(s_full)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref))
    np.testing.assert_array_equal(np.asarray(b), np.asarray(b_ref))

    s4 = sparsify(d, 4)
    a4, b4 = to_beta(s4)
    np.testing.assert_array_equal(np.asarray(a4), np.asarray(a_ref))
    np.testing.assert_allclose(np.asarray(b4), np.asarray(b_ref),
                               rtol=1e-6, atol=1e-6)
    # the tracked set is the true top-4 off-diagonal per row
    eye = np.eye(C, dtype=bool)
    off = np.where(eye, -np.inf, np.asarray(d))
    want_idx = np.argsort(off, axis=-1)[..., ::-1][..., :4]
    np.testing.assert_array_equal(np.sort(np.asarray(s4.idx), -1),
                                  np.sort(want_idx, -1))


def test_scatter_row_tracks_dense_update():
    """A long random update stream through the sparse scatter keeps the
    labeled rows' Beta parameters glued to the dense reference (exact
    diagonal, mass-conserving off-diagonal), and the full layout applies
    bitwise-identical float ops."""
    from coda_tpu.ops.beta import dirichlet_to_beta
    from coda_tpu.ops.sparse_rows import row_beta, scatter_row, sparsify

    H, C, lr = 5, 9, 0.05
    d = _rand_dirichlets(jax.random.PRNGKey(1), H, C)
    s_full = sparsify(d, C)
    s3 = sparsify(d, 3)
    scatter = jax.jit(scatter_row, static_argnames=())
    rng = np.random.default_rng(2)
    for t in range(200):
        tc = jnp.asarray(int(rng.integers(0, C)))
        preds = jnp.asarray(rng.integers(0, C, H).astype(np.int32))
        onehot = jax.nn.one_hot(preds, C, dtype=d.dtype)
        d = d.at[:, tc, :].add(lr * onehot)
        s_full = scatter(s_full, tc, preds, lr)
        s3 = scatter(s3, tc, preds, lr)
    a_ref, b_ref = dirichlet_to_beta(d)
    for c in range(C):
        a_f, b_f = row_beta(s_full, jnp.asarray(c))
        np.testing.assert_array_equal(np.asarray(a_f),
                                      np.asarray(a_ref[:, c]))
        np.testing.assert_array_equal(np.asarray(b_f),
                                      np.asarray(b_ref[:, c]))
        a_3, b_3 = row_beta(s3, jnp.asarray(c))
        np.testing.assert_array_equal(np.asarray(a_3),
                                      np.asarray(a_ref[:, c]))
        # mass conservation: 200 rounds of share-transfer rounding stay
        # at float-drift level, nowhere near the 2.34e-4 score contract
        np.testing.assert_allclose(np.asarray(b_3),
                                   np.asarray(b_ref[:, c]),
                                   rtol=2e-5, atol=2e-5)


def test_scatter_eviction_inserts_heavy_untracked_column():
    """An untracked column that accumulates real mass displaces the
    smallest tracked entry (which returns to the residual) — confusion
    that concentrates later in the run is re-captured, not lost."""
    from coda_tpu.ops.sparse_rows import (
        densify_row,
        row_beta,
        scatter_row,
        sparsify,
    )

    H, C, K = 1, 8, 2
    d = jnp.full((H, C, C), 0.01).at[0, 0, 0].set(1.0)
    d = d.at[0, 0, 1].set(0.5).at[0, 0, 2].set(0.4)   # tracked: {1, 2}
    s = sparsify(d, K)
    assert set(np.asarray(s.idx)[0, 0].tolist()) == {1, 2}
    # hammer column 5 (untracked) with labels for class-0 rows
    for _ in range(4):
        s = scatter_row(s, jnp.asarray(0), jnp.asarray([5], jnp.int32),
                        0.3)
    assert 5 in np.asarray(s.idx)[0, 0].tolist()
    # the evicted entry's mass lives on in the residual, not vanished
    a_t, b_t = row_beta(s, jnp.asarray(0))
    # off-diagonal mass: C-3 cold columns + the two tracked + 4 labels
    want_off = 0.01 * (C - 3) + 0.5 + 0.4 + 4 * 0.3
    np.testing.assert_allclose(float(b_t[0]), want_off, rtol=1e-5)
    # densify spreads the residual over untracked columns only
    row = np.asarray(densify_row(s, jnp.asarray(0)))[0]
    assert row[0] == pytest.approx(1.0)
    assert row.sum() == pytest.approx(1.0 + want_off, rel=1e-5)


# ---------------------------------------------------------------------------
# end-to-end parity / contract
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not os.path.exists(_DIGITS),
                    reason="committed digits task not present")
def test_sparse_untruncated_bitwise_digits_trace():
    """THE parity rung: sparse:K=C on the REAL digits task is bitwise
    equal to dense — selection trace, best models, AND the per-round
    scores (same float ops at the same positions)."""
    from coda_tpu.data import Dataset
    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda

    ds = Dataset.from_file(_DIGITS)
    C = ds.preds.shape[-1]
    r_dense = run_experiment(
        make_coda(ds.preds, CODAHyperparams(eig_mode="incremental")),
        ds, iters=30, seed=0)
    r_sparse = run_experiment(
        make_coda(ds.preds, CODAHyperparams(eig_mode="incremental",
                                            posterior=f"sparse:{C}")),
        ds, iters=30, seed=0)
    for name in ("chosen_idx", "best_model", "select_prob", "regret"):
        np.testing.assert_array_equal(
            np.asarray(getattr(r_dense, name)),
            np.asarray(getattr(r_sparse, name)), err_msg=name)


def _record(factory, task, iters=25, posterior="dense", extra_knobs=None):
    from coda_tpu.engine.loop import run_seeds_recorded
    from coda_tpu.telemetry.recorder import (
        RunRecord,
        environment_fingerprint,
    )

    res, aux = run_seeds_recorded(factory, task.preds, task.labels,
                                  iters=iters, seeds=1, trace_k=5)
    knobs = dict({"method": "coda", "posterior": posterior},
                 **(extra_knobs or {}))
    fp = environment_fingerprint(dataset=task, knobs=knobs)
    return RunRecord.from_result(
        res, aux, fp, run={"task": task.name, "iters": iters, "seeds": 1})


@pytest.mark.skipif(not os.path.exists(_DIGITS),
                    reason="committed digits task not present")
def test_sparse_truncated_score_contract_with_triage():
    """sparse:K<C vs dense through the replay comparison path: scores
    within the documented 2.34e-4 contract; if the trace diverges at all
    the first divergence is a CLASSIFIED near-tie flip."""
    from coda_tpu.data import Dataset
    from coda_tpu.engine.replay import compare_records
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.telemetry.recorder import CROSS_BACKEND_SCORE_TOL

    ds = Dataset.from_file(_DIGITS)
    rec_d = _record(lambda p: make_coda(p, CODAHyperparams(
        eig_mode="incremental")), ds)
    rec_s = _record(lambda p: make_coda(p, CODAHyperparams(
        eig_mode="incremental", posterior="sparse:4")), ds,
        posterior="sparse:4")
    worst = max(
        float(np.max(np.abs(np.asarray(rec_d.arrays[q])
                            - np.asarray(rec_s.arrays[q]))))
        for q in ("topk_score", "chosen_score"))
    assert worst <= CROSS_BACKEND_SCORE_TOL, worst
    report = compare_records(rec_d, rec_s,
                             score_tol=CROSS_BACKEND_SCORE_TOL)
    assert report.meta.get("knob_diff") == {
        "posterior": ["dense", "sparse:4"]}
    for seed in report.seeds:
        assert seed.parity or seed.classification == "tie-break-flip", (
            seed.classification)


def test_sparse_requires_incremental_tier():
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.selectors import CODAHyperparams, make_coda

    t = make_synthetic_task(seed=1, H=4, N=32, C=4)
    with pytest.raises(ValueError, match="incremental EIG tier"):
        make_coda(t.preds, CODAHyperparams(eig_mode="factored",
                                           posterior="sparse:2"))
    with pytest.raises(ValueError, match="unknown posterior"):
        make_coda(t.preds, CODAHyperparams(posterior="sparse:nope"))


# ---------------------------------------------------------------------------
# auto-tier budget at the ImageNet boundary
# ---------------------------------------------------------------------------

def test_resolver_pins_imagenet_shape_tiers():
    """The C=1000 boundary (ISSUE 9 satellite): pin what auto picks for
    the ImageNet pool shape in BOTH representations, and pin the shape
    where the dense (H, C, C) carry is what blows the budget — so a
    budget edit that silently flips the C=1000 tier fails here."""
    from coda_tpu.selectors import CODAHyperparams
    from coda_tpu.selectors.coda import resolve_eig_mode

    H, N, C = 500, 256, 1000   # the IMAGENET_VIRTUAL_r05 pool shape
    assert resolve_eig_mode(
        CODAHyperparams(), H, N, C) == "incremental"
    assert resolve_eig_mode(
        CODAHyperparams(posterior="sparse:32"), H, N, C) == "incremental"
    # vmapped seeds multiply every resident tensor: 5 dense replicas blow
    # the cache budget AND the factored-tables budget -> rowscan
    assert resolve_eig_mode(
        CODAHyperparams(n_parallel=5), H, N, C) == "rowscan"

    # 4x the model pool: the dense posterior alone is 8 GB — past the
    # budget, and past the factored tables too (16*C*H*G = 8 GB), so
    # dense lands on rowscan; the sparse representation of the SAME
    # shape stays incremental — the tier the sparse:K rung exists for
    H2, N2 = 2000, 64
    assert resolve_eig_mode(
        CODAHyperparams(), H2, N2, C) == "rowscan"
    assert resolve_eig_mode(
        CODAHyperparams(posterior="sparse:32"), H2, N2, C) == "incremental"


# ---------------------------------------------------------------------------
# the amortized P(best) rung
# ---------------------------------------------------------------------------

def test_amortized_below_gate_is_bitwise():
    """At the default prior concentration (~4.2, below the committed
    gate) every round refreshes through the exact quadrature: the knob
    changes NOTHING — bitwise, not just close."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine import run_experiment
    from coda_tpu.selectors import CODAHyperparams, make_coda

    t = make_synthetic_task(seed=3, H=8, N=200, C=6)
    rq = run_experiment(make_coda(t.preds, CODAHyperparams(
        eig_mode="incremental", eig_chunk=64)), t, iters=20, seed=0)
    ra = run_experiment(make_coda(t.preds, CODAHyperparams(
        eig_mode="incremental", eig_chunk=64,
        eig_pbest="amortized")), t, iters=20, seed=0)
    for name in ("chosen_idx", "best_model", "select_prob"):
        np.testing.assert_array_equal(np.asarray(getattr(rq, name)),
                                      np.asarray(getattr(ra, name)),
                                      err_msg=name)


def test_amortized_engaged_holds_score_contract():
    """Above the gate (multiplier-concentrated prior) the logistic-normal
    tables ARE in the loop — scores move, but stay within the committed
    2.34e-4 contract, and the cached P(best) rows (best-model readout /
    recorder digests) remain quadrature-exact."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.selectors import CODAHyperparams, make_coda
    from coda_tpu.selectors.coda import _AMORTIZED_MIN_CONC
    from coda_tpu.telemetry.recorder import CROSS_BACKEND_SCORE_TOL

    t = make_synthetic_task(seed=3, H=8, N=200, C=6)
    hp_q = CODAHyperparams(eig_mode="incremental", eig_chunk=64,
                           multiplier=20.0)
    hp_a = hp_q._replace(eig_pbest="amortized")
    rec_q = _record(lambda p: make_coda(p, hp_q), t, iters=20)
    rec_a = _record(lambda p: make_coda(p, hp_a), t, iters=20,
                    extra_knobs={"eig_pbest": "amortized"})
    d_score = max(
        float(np.max(np.abs(np.asarray(rec_q.arrays[q])
                            - np.asarray(rec_a.arrays[q]))))
        for q in ("topk_score", "chosen_score"))
    assert 0.0 < d_score <= CROSS_BACKEND_SCORE_TOL, d_score
    # gate sanity: multiplier=20 puts every row past the threshold
    sel = make_coda(t.preds, hp_q)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    conc = np.asarray(state.dirichlets.sum(-1))
    assert conc.min() >= _AMORTIZED_MIN_CONC
    # the cached P(best) rows stay quadrature-exact: the posterior digest
    # is BITWISE while the two runs still share a trajectory (after a
    # near-tie pick flips, the labeled sets differ and digests follow)
    idx_q = np.asarray(rec_q.arrays["chosen_idx"])[0]
    idx_a = np.asarray(rec_a.arrays["chosen_idx"])[0]
    diverge = np.nonzero(idx_q != idx_a)[0]
    shared = int(diverge[0]) if diverge.size else len(idx_q)
    np.testing.assert_array_equal(
        np.asarray(rec_q.arrays["pbest_max"])[0, :shared],
        np.asarray(rec_a.arrays["pbest_max"])[0, :shared])


def test_amortized_hyp_row_accuracy_at_gate():
    """Unit-level calibration pin: at the committed gate concentration
    the amortized hypothetical rows track the quadrature's closely
    enough to carry the measured end-to-end bound."""
    from coda_tpu.selectors.coda import (
        _AMORTIZED_MIN_CONC,
        _pbest_hyp_row,
        _pbest_hyp_row_amortized,
    )

    rng = np.random.default_rng(0)
    H, B = 24, 64
    mean = rng.uniform(0.55, 0.9, H)
    a = jnp.asarray((mean * _AMORTIZED_MIN_CONC).astype(np.float32))
    b = jnp.asarray(_AMORTIZED_MIN_CONC - np.asarray(a))
    eq = jnp.asarray(rng.random((B, H)) < 0.2)
    hq = np.asarray(_pbest_hyp_row(a, b, eq, 1.0, 256))
    ha = np.asarray(_pbest_hyp_row_amortized(a, b, eq, 1.0, 256))
    assert np.max(np.abs(hq - ha)) < 0.05  # the per-row bridge error...
    # ...which the normalized entropy-difference scoring chain contracts
    # to the measured <=1.44e-4 (see _AMORTIZED_MIN_CONC's calibration)


def test_amortized_guards():
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.selectors import CODAHyperparams, make_coda

    t = make_synthetic_task(seed=1, H=4, N=32, C=4)
    with pytest.raises(ValueError, match="unknown eig_pbest"):
        make_coda(t.preds, CODAHyperparams(eig_pbest="laplace"))
    with pytest.raises(ValueError, match="amortized"):
        make_coda(t.preds, CODAHyperparams(eig_mode="factored",
                                           eig_pbest="amortized"))
    with pytest.raises(ValueError, match="amortized"):
        make_coda(t.preds, CODAHyperparams(
            eig_mode="incremental", eig_backend="pallas",
            eig_pbest="amortized"))


# ---------------------------------------------------------------------------
# plumbing: CLI, fingerprint, replay auto-tolerance
# ---------------------------------------------------------------------------

def test_cli_posterior_plumbs_to_selector():
    from coda_tpu.cli import build_selector_factory, parse_args
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine import run_experiment

    t = make_synthetic_task(seed=3, H=5, N=48, C=4)
    args = parse_args(["--synthetic", "5,48,4", "--method", "coda",
                       "--posterior", "sparse:2", "--eig-pbest",
                       "amortized", "--eig-chunk", "48"])
    sel = build_selector_factory(args, "synthetic")(t.preds)
    assert sel.hyperparams["posterior"] == "sparse:2"
    assert sel.hyperparams["eig_pbest"] == "amortized"
    res = run_experiment(sel, t, iters=5, seed=0)
    assert np.isfinite(np.asarray(res.regret)).all()


def test_posterior_knob_is_fingerprinted_and_drives_auto_tol():
    """The ISSUE 9 satellite: the recorder fingerprints the posterior
    representation, and replay's auto tolerance keys off it — dense vs
    sparse records compare under the documented score contract, two
    same-representation records stay bitwise."""
    import argparse

    from coda_tpu.engine.replay import _auto_tol
    from coda_tpu.telemetry.recorder import (
        CROSS_BACKEND_SCORE_TOL,
        KNOB_FIELDS,
        RunRecord,
        knobs_from_args,
    )

    assert "posterior" in KNOB_FIELDS and "eig_pbest" in KNOB_FIELDS
    ns = argparse.Namespace(method="coda", posterior="sparse:32",
                            eig_pbest="quad")
    knobs = knobs_from_args(ns)
    assert knobs["posterior"] == "sparse:32"

    def rec(posterior):
        return RunRecord(meta={"fingerprint": {
            "backend": "cpu", "knobs": {"method": "coda",
                                        "posterior": posterior}}})

    dense, sparse = rec("dense"), rec("sparse:32")
    assert _auto_tol(dense, {}, against=rec("dense")) == 0.0
    assert _auto_tol(dense, {},
                     against=sparse) == CROSS_BACKEND_SCORE_TOL


def test_bench_imagenet_preset_and_posterior_model():
    """bench.py's imagenet preset reproduces the r05 pool shape, and its
    analytic byte model prices the posterior stream per representation."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench

    assert bench.BENCH_CONFIGS["imagenet"][:3] == (500, 256, 1000)
    H, N, C = 500, 256, 1000
    b_dense = bench._analytic_step_bytes(H, N, C, "incremental",
                                         pi_update="delta")
    b_sparse = bench._analytic_step_bytes(H, N, C, "incremental",
                                          pi_update="delta",
                                          posterior="sparse:32")
    assert b_dense - b_sparse == 4.0 * H * C * C - 16.0 * H * 32
    # the dense posterior stream dominates this shape's per-round bytes
    assert (b_dense - b_sparse) / b_dense > 0.5


def test_imagenet_sparse_capture_smoke(tmp_path):
    """The capture pipeline end to end at the CI shape: mesh execution,
    recording, the REAL `cli replay --against` with auto tolerance, and
    a self-consistent artifact (the committed-shape bounds are gated by
    scripts/check_perf.py on the committed artifact instead)."""
    import subprocess
    import sys

    out = tmp_path / "IMAGENET_SPARSE_smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "scripts/imagenet_sparse.py", "--small",
         "--out", str(out), "--record-root", str(tmp_path / "records")],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        capture_output=True, text=True, timeout=560, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    import json

    rep = json.loads(out.read_text())
    assert rep["ok"] is True
    assert rep["replay"]["max_abs_dscore"] <= rep["replay"]["score_tol"]
    assert rep["replay"]["knob_diff"] == {
        "posterior": ["sparse:8", "dense"]}
    assert (tmp_path / "records" / "sparse" / "record.json").exists()
