"""In-process suite runner: compile reuse across same-shape tasks, DB layout
compatible with the analysis SQL, and DB-checked resume (the capability of
the reference's SLURM fan-out, in one process)."""

from __future__ import annotations

import os

import numpy as np
import pytest


@pytest.fixture()
def three_tasks(tmp_path):
    from coda_tpu.data import Dataset, make_synthetic_task

    # two tasks share a shape (compile reuse), one differs
    t1 = make_synthetic_task(seed=1, H=4, N=40, C=3, name="alpha")
    t2 = make_synthetic_task(seed=2, H=4, N=40, C=3, name="beta")
    t3 = make_synthetic_task(seed=3, H=3, N=24, C=4, name="gamma")
    return [t1, t2, t3]


def test_suite_runs_and_reuses_compiles(three_tasks):
    from coda_tpu.engine.suite import SuiteRunner

    runner = SuiteRunner(iters=4, seeds=2)
    results = runner.run(three_tasks, ["iid", "coda"], progress=lambda s: None)
    assert len(results) == 6
    for (task, method), res in results.items():
        assert np.asarray(res.regret).shape == (2, 4)
        assert np.isfinite(np.asarray(res.regret)).all()
    # one jitted callable per method — shapes re-specialize inside jax's
    # cache, the wrapper count must not grow with task count
    assert len(runner._jitted) == 2
    # same-shape tasks share an executable but still get their own data:
    # CODA's (data-dependent) traces must differ between alpha and beta
    # (IID's wouldn't — it ignores preds and reuses the same seed keys)
    a = np.asarray(results[("alpha", "coda")].chosen_idx)
    b = np.asarray(results[("beta", "coda")].chosen_idx)
    assert not np.array_equal(a, b)


def test_suite_seed_dedup(three_tasks):
    """Deterministic methods run seed 0 once and broadcast (reference
    main.py:128-130); stochastic methods still get distinct seeds."""
    from coda_tpu.engine.suite import SuiteRunner

    runner = SuiteRunner(iters=4, seeds=3)
    # uncertainty is deterministic (non-adaptive argmax, tie-free scores)
    res = runner.run_one("uncertainty", three_tasks[0])
    idx = np.asarray(res.chosen_idx)
    assert idx.shape == (3, 4)
    assert (idx == idx[0]).all()
    # iid is stochastic by construction: seeds differ
    res = runner.run_one("iid", three_tasks[0])
    idx = np.asarray(res.chosen_idx)
    seqs = {tuple(r) for r in idx}
    assert len(seqs) > 1


def test_suite_batched_matches_unbatched(three_tasks):
    """run_batched must reproduce run()'s per-task results EXACTLY for
    same-shape groups — deterministic methods broadcast from the same
    probe, stochastic methods use the same seed keys — while dispatching
    one vmapped program pair per (group, method)."""
    from coda_tpu.engine.suite import SuiteRunner

    same_shape = three_tasks[:2]  # alpha + beta share (4, 40, 3)
    methods = ["iid", "uncertainty", "coda"]
    r_un = SuiteRunner(iters=4, seeds=3).run(
        list(same_shape), methods, progress=lambda s: None)
    r_ba = SuiteRunner(iters=4, seeds=3).run_batched(
        [same_shape], methods, progress=lambda s: None)
    assert set(r_un) == set(r_ba)
    for key in r_un:
        for a, b in zip(r_un[key], r_ba[key]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=str(key))


def test_suite_batched_guards():
    """Mixed shapes raise; mixed per-task TASK_EPS values batch fine (ε is
    a runtime argument) and reproduce the unbatched per-task results."""
    import pytest as _pytest

    from coda_tpu.data import Dataset, make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner

    t1 = make_synthetic_task(seed=1, H=4, N=40, C=3, name="alpha")
    t3 = make_synthetic_task(seed=3, H=3, N=24, C=4, name="gamma")
    runner = SuiteRunner(iters=2, seeds=2)
    with _pytest.raises(ValueError, match="mixes shapes"):
        runner.run_batched([[t1, t3]], ["iid"], progress=lambda s: None)
    # wine (0.37) vs digits (0.39) resolve different tuned epsilons —
    # they share one executable, each task seeing its own traced ε
    ta = Dataset(preds=t1.preds, labels=t1.labels, name="wine")
    tb = Dataset(preds=t1.preds, labels=t1.labels, name="digits")
    r_ba = runner.run_batched([[ta, tb]], ["model_picker"],
                              progress=lambda s: None)
    r_un = SuiteRunner(iters=2, seeds=2).run(
        [ta, tb], ["model_picker"], progress=lambda s: None)
    assert set(r_ba) == set(r_un)
    for key in r_un:
        for a, b in zip(r_un[key], r_ba[key]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=str(key))


def test_suite_modelpicker_per_task_epsilon():
    """Task-dependent TASK_EPS is a RUNTIME argument: same-shape tasks with
    different tuned epsilons share ONE executable per width (ε never keys
    the compile cache), yet each task's trace uses its own ε — pinned by
    comparing against selectors built with the ε baked in."""
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.loop import run_seeds
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.selectors import TASK_EPS, make_modelpicker

    mk = lambda name: make_synthetic_task(seed=1, H=4, N=40, C=3, name=name)
    runner = SuiteRunner(iters=4, seeds=2)
    results = {}
    for name in ("real_painting", "iwildcam", "cifar10_4070", "glue/qqp"):
        results[name] = runner.run_one("model_picker", mk(name))
    # one executable per width (probe and rest are both width 1 at
    # seeds=2), NOT per distinct ε
    assert len(runner._jitted) == 1
    assert all("epsilon" not in dict(k[1]) for k in runner._jitted)
    for name in ("real_painting", "iwildcam"):  # eps 0.35 vs 0.49
        ds = mk(name)
        sel = make_modelpicker(ds.preds, epsilon=TASK_EPS[name])
        ref = run_seeds(sel, ds, iters=4, seeds=2)
        np.testing.assert_array_equal(
            np.asarray(results[name].chosen_idx),
            np.asarray(ref.chosen_idx), err_msg=name)
        np.testing.assert_array_equal(
            np.asarray(results[name].regret),
            np.asarray(ref.regret), err_msg=name)


def test_suite_resume_skips_deterministic(three_tasks, tmp_path):
    """Deterministic pairs broadcast the seed-0 result but still log every
    seed child, so the all-children resume check skips them on rerun."""
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(str(tmp_path / "s.sqlite"))
    runner = SuiteRunner(iters=3, seeds=3)
    runner.run(three_tasks[:1], ["uncertainty"], store=store,
               progress=lambda s: None)
    msgs: list[str] = []
    out = runner.run(three_tasks[:1], ["uncertainty"], store=store,
                     progress=msgs.append)
    assert out == {}
    assert any("skip" in m for m in msgs)
    store.close()


def test_suite_logs_and_resumes(three_tasks, tmp_path):
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(str(tmp_path / "s.sqlite"))
    runner = SuiteRunner(iters=3, seeds=2)
    msgs: list[str] = []
    runner.run(three_tasks[:1], ["iid"], store=store, progress=msgs.append)
    # same layout the reference analysis SQL joins on
    rows = store.query(
        """SELECT m.step, m.value FROM metrics m
           JOIN tags t ON t.run_uuid = m.run_uuid AND t.key='mlflow.runName'
           WHERE t.value='alpha-iid-0' AND m.key='regret' ORDER BY m.step"""
    )
    assert [s for s, _ in rows] == [1, 2, 3]
    # rerun: the finished pair is skipped via the DB
    msgs.clear()
    out = runner.run(three_tasks[:1], ["iid"], store=store,
                     progress=msgs.append)
    assert out == {}
    assert any("skip" in m for m in msgs)
    store.close()


def test_run_suite_cli(three_tasks, tmp_path):
    """End-to-end through the script with .npz files on disk."""
    import importlib.util

    npdir = tmp_path / "preds"
    npdir.mkdir()
    for t in three_tasks:
        np.savez(npdir / f"{t.name}.npz", preds=np.asarray(t.preds),
                 labels=np.asarray(t.labels))
    spec = importlib.util.spec_from_file_location(
        "run_suite",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "run_suite.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    db = str(tmp_path / "db.sqlite")
    mod.main(["--pred-dir", str(npdir), "--db", db, "--methods",
              "iid", "--seeds", "2", "--iters", "3"])
    from coda_tpu.tracking import TrackingStore

    store = TrackingStore(db)
    (n,) = store.query("SELECT COUNT(*) FROM experiments")[0]
    assert n == 3
    store.close()


def test_suite_sharded_task_matches_unsharded():
    """A task sharded over a (data x model) mesh must produce the same
    traces through the suite runner as its unsharded copy (same jitted
    program; GSPMD inserts the collectives)."""
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.parallel import make_mesh, preds_sharding

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    sharding = preds_sharding(make_mesh(data=4, model=2))
    plain = make_synthetic_task(seed=9, H=4, N=40, C=3, name="shardtask")
    sharded = make_synthetic_task(seed=9, H=4, N=40, C=3, name="shardtask",
                                  sharding=sharding)
    assert sharded.preds.sharding.num_devices == 8

    runner = SuiteRunner(iters=5, seeds=2)
    r_plain = runner.run([plain], ["iid", "coda"], progress=lambda s: None)
    r_shard = runner.run([sharded], ["iid", "coda"], progress=lambda s: None)
    for key in r_plain:
        np.testing.assert_array_equal(
            np.asarray(r_plain[key].chosen_idx),
            np.asarray(r_shard[key].chosen_idx))
        np.testing.assert_array_equal(
            np.asarray(r_plain[key].best_model),
            np.asarray(r_shard[key].best_model))


def test_suite_width_divergent_eig_tiers(monkeypatch):
    """When the 1-seed dedup probe fits the incremental cache but the
    (seeds-1) batch does not, the two batches compile different EIG tiers
    of the same integral; the concatenated result must stay consistent."""
    import jax.numpy as jnp

    import coda_tpu.selectors.coda as coda_mod
    from coda_tpu.data import Dataset, make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner
    from coda_tpu.selectors import CODAHyperparams
    from coda_tpu.selectors.coda import resolve_eig_mode

    base = make_synthetic_task(seed=3, H=4, N=24, C=3)
    # duplicate every point: EIG scores tie exactly, the probe reports
    # stochastic=True, and the remaining-seeds batch actually runs
    preds = jnp.concatenate([base.preds, base.preds], axis=1)
    labels = jnp.concatenate([base.labels, base.labels])
    task = Dataset(preds=preds, labels=labels, name="ties")
    H, N, C = task.preds.shape

    # budget: one (N, C, H) cache fits (plus the tiny dense-posterior
    # charge the budget now includes), four do not
    one_cache = 4 * N * C * H
    monkeypatch.setattr(coda_mod, "_INCR_CACHE_MAX_BYTES",
                        2 * one_cache + 4 * H * C * C)
    assert resolve_eig_mode(
        CODAHyperparams(n_parallel=1), H, N, C) == "incremental"
    assert resolve_eig_mode(
        CODAHyperparams(n_parallel=4), H, N, C) == "factored"

    runner = SuiteRunner(iters=5, seeds=5)
    res = runner.run_one("coda", task)
    assert np.asarray(res.stochastic).all()
    assert np.asarray(res.regret).shape == (5, 5)
    assert np.isfinite(np.asarray(res.regret)).all()
    # both widths were compiled (probe + rest), at their own tiers
    widths = {k[2] for k in runner._jitted}
    assert widths == {1, 4}


def test_suite_batched_single_task_group():
    """A T=1 group (batch-cap remainder, or a resume leaving one unfinished
    task) must dispatch: runtime hyperparams stay rank-1 under the task
    vmap even at T=1."""
    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.suite import SuiteRunner

    t = make_synthetic_task(seed=1, H=4, N=40, C=3, name="wine")
    r_ba = SuiteRunner(iters=2, seeds=2).run_batched(
        [[t]], ["model_picker", "iid"], progress=lambda s: None)
    r_un = SuiteRunner(iters=2, seeds=2).run(
        [t], ["model_picker", "iid"], progress=lambda s: None)
    for key in r_un:
        for a, b in zip(r_un[key], r_ba[key]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=str(key))


def test_suite_batched_caps_split_dispatches(three_tasks):
    """batch_caps sub-chunks a group per method (int or shape-callable);
    results still match the unbatched run exactly."""
    from coda_tpu.engine.suite import SuiteRunner

    same_shape = three_tasks[:2]
    r_un = SuiteRunner(iters=3, seeds=2).run(
        list(same_shape), ["coda", "iid"], progress=lambda s: None)
    runner = SuiteRunner(iters=3, seeds=2)
    r_ba = runner.run_batched(
        [same_shape], ["coda", "iid"],
        batch_caps={"coda": 1, "iid": lambda H, N, C: 2},
        progress=lambda s: None)
    coda_pairs = [p for p in runner.last_stats["pairs"]
                  if p["method"] == "coda"]
    assert [p["batched"] for p in coda_pairs] == [1, 1]
    iid_pairs = [p for p in runner.last_stats["pairs"]
                 if p["method"] == "iid"]
    assert [p["batched"] for p in iid_pairs] == [2, 2]
    for key in r_un:
        for a, b in zip(r_un[key], r_ba[key]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=str(key))
