import numpy as np
import jax.numpy as jnp
import pytest

from coda_tpu.data import Dataset, make_synthetic_task
from coda_tpu.losses import LOSS_FNS, accuracy_loss, cross_entropy_loss
from coda_tpu.oracle import Oracle, true_losses


def test_synthetic_task_shapes_and_validity():
    ds = make_synthetic_task(seed=3, H=6, N=100, C=5)
    H, N, C = ds.shape
    assert (H, N, C) == (6, 100, 5)
    assert ds.preds.dtype == jnp.float32
    p = np.asarray(ds.preds)
    assert np.all(p >= 0)
    np.testing.assert_allclose(p.sum(-1), 1.0, atol=1e-5)
    labels = np.asarray(ds.labels)
    assert labels.min() >= 0 and labels.max() < C


def test_synthetic_task_deterministic():
    a = make_synthetic_task(seed=7, H=3, N=20, C=3)
    b = make_synthetic_task(seed=7, H=3, N=20, C=3)
    np.testing.assert_array_equal(np.asarray(a.preds), np.asarray(b.preds))
    np.testing.assert_array_equal(np.asarray(a.labels), np.asarray(b.labels))


def test_synthetic_accuracies_spread():
    ds = make_synthetic_task(seed=0, H=8, N=2000, C=4, acc_lo=0.3, acc_hi=0.9)
    losses = np.asarray(true_losses(ds.preds, ds.labels))
    # spread of model qualities: the best clearly beats the worst
    assert losses.min() < 0.2
    assert losses.max() > 0.55


def test_npy_roundtrip(tmp_path):
    ds = make_synthetic_task(seed=1, H=4, N=30, C=3)
    fp = tmp_path / "toy.npy"
    np.save(fp, np.asarray(ds.preds))
    np.save(tmp_path / "toy_labels.npy", np.asarray(ds.labels))
    loaded = Dataset.from_file(str(fp))
    assert loaded.name == "toy"
    np.testing.assert_array_equal(np.asarray(loaded.preds), np.asarray(ds.preds))
    np.testing.assert_array_equal(np.asarray(loaded.labels), np.asarray(ds.labels))


def test_pt_roundtrip(tmp_path):
    torch = __import__("torch")
    ds = make_synthetic_task(seed=2, H=3, N=10, C=3)
    fp = tmp_path / "toy.pt"
    torch.save(torch.from_numpy(np.asarray(ds.preds)), fp)
    torch.save(torch.from_numpy(np.asarray(ds.labels)), tmp_path / "toy_labels.pt")
    loaded = Dataset.from_file(str(fp))
    np.testing.assert_allclose(
        np.asarray(loaded.preds), np.asarray(ds.preds), rtol=1e-6
    )


def test_accuracy_loss_matches_manual(tiny_task):
    losses = accuracy_loss(tiny_task.preds, tiny_task.labels[None, :])
    p = np.asarray(tiny_task.preds)
    lab = np.asarray(tiny_task.labels)
    manual = 1.0 - (p.argmax(-1) == lab[None, :]).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(losses), manual)


def test_accuracy_loss_onehot_labels(tiny_task):
    onehot = np.eye(tiny_task.shape[2], dtype=np.float32)[np.asarray(tiny_task.labels)]
    losses = accuracy_loss(tiny_task.preds, jnp.asarray(onehot[None]))
    manual = accuracy_loss(tiny_task.preds, tiny_task.labels[None, :])
    np.testing.assert_array_equal(np.asarray(losses), np.asarray(manual))


def test_cross_entropy_loss():
    preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]], jnp.float32)
    labels = jnp.asarray([0, 1])
    ce = np.asarray(cross_entropy_loss(preds, labels))
    np.testing.assert_allclose(ce, -np.log([0.7, 0.8]), rtol=1e-3)
    assert set(LOSS_FNS) >= {"acc", "ce"}


def test_oracle(tiny_task):
    oracle = Oracle(tiny_task)
    losses = np.asarray(oracle.true_losses(tiny_task.preds))
    assert losses.shape == (tiny_task.shape[0],)
    assert np.all((0 <= losses) & (losses <= 1))
    idx = 5
    assert oracle(idx) == int(tiny_task.labels[idx])


def test_oracle_requires_labels(tiny_task):
    ds = Dataset(preds=tiny_task.preds, labels=None)
    with pytest.raises(ValueError):
        Oracle(ds)


def test_unsharded_fallback_places_on_one_device():
    """A shape that doesn't divide the mesh must degrade to unsharded
    placement (with a warning) when unsharded_fallback is set, and raise
    when it isn't — exercised against real device placement, not error
    strings."""
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.parallel import make_mesh, preds_sharding

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    sharding = preds_sharding(make_mesh(data=4, model=2))

    # N=41 not divisible by data=4: fallback path
    t = make_synthetic_task(seed=1, H=4, N=41, C=3, sharding=sharding,
                            unsharded_fallback=True)
    assert t.preds.sharding.num_devices == 1

    with pytest.raises(ValueError):
        make_synthetic_task(seed=1, H=4, N=41, C=3, sharding=sharding)

    # divisible: sharded for real either way
    t2 = make_synthetic_task(seed=1, H=4, N=40, C=3, sharding=sharding,
                             unsharded_fallback=True)
    assert t2.preds.sharding.num_devices == 8
