"""Figure 5: per-task cumulative-regret curves for every benchmark task in
a grid (capability parity with reference ``paper/fig5.py``: same 4-row task
layout; tasks missing from the DB are skipped).

Usage: python paper/fig5.py [--db coda.sqlite] [--out fig5.pdf]
"""

from __future__ import annotations

import argparse

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import seaborn as sns

from common import CODA_NAME, GLOBAL_METHODS, load_metric, tasks_in

TASK_LAYOUT = [
    ["painting_real", "painting_sketch", "painting_clipart",
     "sketch_painting", "sketch_real", "sketch_clipart"],
    ["clipart_real", "clipart_sketch", "clipart_painting",
     "real_painting", "real_sketch", "real_clipart"],
    ["iwildcam", "fmow", "civilcomments", "camelyon",
     "cifar10_4070", "cifar10_5592", "pacs"],
    ["glue/cola", "glue/mnli", "glue/qnli", "glue/qqp",
     "glue/rte", "glue/sst2", "glue/mrpc"],
]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--db", default="coda.sqlite")
    p.add_argument("--metric", default="cumulative regret")
    p.add_argument("--coda-name", default=CODA_NAME)
    p.add_argument("--out", default="fig5.pdf")
    args = p.parse_args(argv)

    df = load_metric(args.db, args.metric, coda_name=args.coda_name)
    if df.empty:
        raise SystemExit(f"No '{args.metric}' rows in {args.db}")
    methods = [m for m in GLOBAL_METHODS if m in set(df.method)]
    present = set(df.task)
    layout = [[t for t in row if t in present] for row in TASK_LAYOUT]
    layout = [row for row in layout if row]
    known = {t for row in layout for t in row}
    extra = [t for t in tasks_in(df) if t not in known]
    if extra:
        layout.append(extra)
    if not layout:
        raise SystemExit("No tasks in the DB")

    ncols = max(len(r) for r in layout)
    palette = sns.color_palette("colorblind", n_colors=len(methods))
    colors = dict(zip(methods, palette[::-1]))
    fig, axes = plt.subplots(len(layout), ncols,
                             figsize=(2.4 * ncols, 2.2 * len(layout)),
                             squeeze=False)
    for r, row in enumerate(layout):
        for c in range(ncols):
            ax = axes[r][c]
            if c >= len(row):
                ax.axis("off")
                continue
            t = row[c]
            sub = df[df.task == t]
            for m in methods:
                curve = (sub[sub.method == m].sort_values("step"))
                if curve.empty:
                    continue
                lw = 2.0 if m.startswith("CODA") else 1.2
                ax.plot(curve["step"], curve["value"], label=m,
                        color=colors[m], linewidth=lw)
            ax.set_title(t, fontsize=8)
    axes[0][0].legend(fontsize=6)
    fig.supxlabel("Number of labels")
    fig.supylabel(f"{args.metric} (x100)")
    fig.tight_layout()
    fig.savefig(args.out)
    print("Wrote", args.out)


if __name__ == "__main__":
    main()
