"""Figure 1: fraction of tasks converged (regret < 1% sustained) vs. label
budget, per method (capability parity with reference ``paper/fig1.py``:
convergence = the first step after which mean regret stays below threshold
for the rest of the run).

Usage: python paper/fig1.py [--db coda.sqlite] [--out fig1.pdf]
"""

from __future__ import annotations

import argparse

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np
import seaborn as sns

from common import CODA_NAME, GLOBAL_METHODS, load_metric, tasks_in

NO_CONVERGENCE = 999


def convergence_steps(df, methods, tasks, threshold=1.0, max_steps=100):
    """{method: {task: first step with regret < threshold sustained}}."""
    out = {m: {} for m in methods}
    for m in methods:
        for t in tasks:
            series = (df[(df.task == t) & (df.method == m)]
                      .sort_values("step")["value"].to_list())
            step = NO_CONVERGENCE
            for start in range(min(len(series), max_steps)):
                if all(v < threshold for v in series[start:]):
                    step = start + 1
                    break
            out[m][t] = step
    return out


def proportions(conv, methods, tasks, max_steps=100):
    prop = {m: np.zeros(max_steps) for m in methods}
    for m in methods:
        for s in range(1, max_steps + 1):
            prop[m][s - 1] = sum(
                conv[m][t] <= s for t in tasks if conv[m][t] != NO_CONVERGENCE
            ) / max(len(tasks), 1)
    return prop


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--db", default="coda.sqlite")
    p.add_argument("--threshold", type=float, default=1.0)
    p.add_argument("--max-steps", type=int, default=100)
    p.add_argument("--coda-name", default=CODA_NAME)
    p.add_argument("--out", default="fig1.pdf")
    args = p.parse_args(argv)

    df = load_metric(args.db, "regret", coda_name=args.coda_name)
    if df.empty:
        raise SystemExit(f"No regret rows in {args.db}")
    methods = [m for m in GLOBAL_METHODS if m in set(df.method)]
    tasks = tasks_in(df)
    conv = convergence_steps(df, methods, tasks, args.threshold,
                             args.max_steps)
    prop = proportions(conv, methods, tasks, args.max_steps)

    palette = sns.color_palette("colorblind", n_colors=len(methods))
    fig, ax = plt.subplots(figsize=(5, 3.2))
    xs = np.arange(1, args.max_steps + 1)
    for m, color in zip(methods, palette[::-1]):
        lw = 2.5 if m.startswith("CODA") else 1.5
        ax.plot(xs, prop[m], label=m, color=color, linewidth=lw)
    ax.set_xlabel("Number of labels")
    ax.set_ylabel(f"Fraction of tasks with\nregret < {args.threshold:g}%")
    ax.set_ylim(0, 1)
    ax.legend(fontsize=8, loc="upper left")
    fig.tight_layout()
    fig.savefig(args.out)
    print("Wrote", args.out)


if __name__ == "__main__":
    main()
