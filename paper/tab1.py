"""Table 1: cumulative regret (x100) at a given step, LaTeX with best bold /
second-best underlined per task (capability parity with reference
``paper/tab1.py``: same SQL shape, same method set and canonical CODA
config, same grouped row layout and highlighting).

Usage: python paper/tab1.py [--db coda.sqlite] [--step 100] [--out tab1.tex]
"""

from __future__ import annotations

import argparse

import numpy as np

from common import (CODA_NAME, GLOBAL_METHODS, TASK_GROUPS, load_metric,
                    tasks_in)


def pretty_task(t: str) -> str:
    if "_" in t and not t.startswith(("glue", "cifar10")):
        src, tgt = t.split("_", 1)
        return f"{src}$\\rightarrow${tgt}"
    if t.startswith("glue/"):
        return t.split("/", 1)[1]
    return {"cifar10_4070": "cifar10-low", "cifar10_5592": "cifar10-high"}.get(t, t)


def build_table(df, methods=GLOBAL_METHODS, groups=None) -> str:
    present_tasks = tasks_in(
        df, [t for g in TASK_GROUPS.values() for t in g])
    if groups is None:
        groups = {g: [t for t in ts if t in present_tasks]
                  for g, ts in TASK_GROUPS.items()}
        groups = {g: ts for g, ts in groups.items() if ts}
        leftover = [t for t in present_tasks
                    if all(t not in ts for ts in groups.values())]
        if leftover:
            groups["Other"] = leftover
    tasks = [t for ts in groups.values() for t in ts]
    methods = [m for m in methods if m in set(df.method)]

    piv = (df.pivot(index="method", columns="task", values="value")
             .reindex(index=methods, columns=tasks))
    vals = piv.to_numpy()
    best = np.nanargmin(vals, axis=0)
    order = np.argsort(vals, axis=0)
    second = order[1] if len(methods) > 1 else best

    lines = [r"\begin{tabular}{cl" + "r" * len(methods) + "}", r"\toprule"]
    header = [r"\textbf{CODA (Ours)}" if m.startswith("CODA") else m
              for m in methods]
    lines.append(r"& Task & " + " & ".join(header) + r" \\")
    lines.append(r"\midrule")
    col = {t: j for j, t in enumerate(tasks)}
    for g_name, g_tasks in groups.items():
        rot = (rf"\parbox[t]{{}}{{\multirow{{{len(g_tasks)}}}{{*}}"
               rf"{{\rotatebox[origin=c]{{90}}{{{g_name}}}}}}}")
        for r_i, t in enumerate(g_tasks):
            cells = []
            j = col[t]
            for i, m in enumerate(methods):
                v = vals[i, j]
                s = "--" if np.isnan(v) else f"{v:.1f}"
                if np.isnan(v):
                    pass  # never highlight a missing cell
                elif best[j] == i:
                    s = rf"\textbf{{{s}}}"
                elif second[j] == i:
                    s = rf"\underline{{{s}}}"
                if m.startswith("CODA"):
                    s = rf"\cellcolor{{gray!15}}{s}"
                cells.append(s)
            start = f"{rot} & " if r_i == 0 else "& "
            lines.append(start + pretty_task(t) + " & "
                         + " & ".join(cells) + r" \\ ")
        lines.append(r"\midrule")
    lines[-1] = r"\bottomrule"
    lines.append(r"\end{tabular}")
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--db", default="coda.sqlite")
    p.add_argument("--metric", default="cumulative regret")
    p.add_argument("--step", type=int, default=100)
    p.add_argument("--coda-name", default=CODA_NAME)
    p.add_argument("--out", default=None, help="write LaTeX here (else stdout)")
    args = p.parse_args(argv)

    df = load_metric(args.db, args.metric, coda_name=args.coda_name,
                     step=args.step)
    if df.empty:
        raise SystemExit(f"No '{args.metric}' rows at step {args.step} "
                         f"in {args.db}")
    latex = build_table(df)
    if args.out:
        with open(args.out, "w") as f:
            f.write(latex + "\n")
        print("Wrote", args.out)
    else:
        print(latex)


if __name__ == "__main__":
    main()
