"""Figure 4: failure-case analysis — runs CODA step-0 in-process on chosen
tasks and plots the true best model's confusion matrix plus the true vs.
estimated class marginal (capability parity with reference ``paper/fig4.py``,
which probes civilcomments and glue_cola to show where the consensus prior
misleads the class-marginal estimate).

Usage: python paper/fig4.py --tasks civilcomments,glue_cola [--data-dir data]
"""

from __future__ import annotations

import argparse
import os
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def probe_task(path: str, ax_cm, ax_marginal, title: str):
    import jax

    from coda_tpu.data import Dataset
    from coda_tpu.losses import accuracy_loss
    from coda_tpu.oracle import true_losses
    from coda_tpu.selectors import make_coda

    ds = Dataset.from_file(path)
    if ds.labels is None:
        raise SystemExit(f"{path} has no labels")
    losses = np.asarray(true_losses(ds.preds, ds.labels, accuracy_loss))
    best_idx = int(losses.argmin())

    sel = make_coda(ds.preds)
    state = jax.jit(sel.init)(jax.random.PRNGKey(0))
    pi_hat = np.asarray(state.pi_hat)

    labels = np.asarray(ds.labels)
    best_preds = np.asarray(ds.preds[best_idx]).argmax(-1)
    C = ds.preds.shape[-1]

    # row-normalized confusion of the true best model
    cm = np.zeros((C, C))
    np.add.at(cm, (labels, best_preds), 1.0)
    cm /= np.clip(cm.sum(axis=1, keepdims=True), 1, None)
    im = ax_cm.imshow(cm, cmap="viridis", vmin=0, vmax=1)
    ax_cm.set_title(f"{title}: true best model")
    ax_cm.set_xlabel("Predicted label")
    ax_cm.set_ylabel("True label")
    plt.colorbar(im, ax=ax_cm, fraction=0.046)

    true_marginal = np.bincount(labels, minlength=C).astype(float)
    true_marginal /= true_marginal.sum()
    xs = np.arange(C)
    ax_marginal.bar(xs - 0.2, true_marginal, width=0.4, label="True")
    ax_marginal.bar(xs + 0.2, pi_hat, width=0.4, label="Est.")
    ax_marginal.set_title(f"{title}: class dist.")
    ax_marginal.set_xlabel("Class idx")
    ax_marginal.set_ylabel("Class proportion")
    ax_marginal.legend(fontsize=8)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tasks", default="civilcomments,glue_cola")
    p.add_argument("--data-dir", default="data")
    p.add_argument("--out", default="fig4.pdf")
    p.add_argument("--platform", default=None)
    args = p.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)

    tasks = args.tasks.split(",")
    paths = []
    for t in tasks:
        from coda_tpu.data import find_task_file

        fp = find_task_file(args.data_dir, t)
        if fp is None:
            print(f"skipping {t}: no data file in {args.data_dir}")
            continue
        paths.append((t, fp))
    if not paths:
        raise SystemExit("No tasks with data found")

    fig, axes = plt.subplots(1, 2 * len(paths),
                             figsize=(5 * len(paths), 2.6), squeeze=False)
    for i, (t, fp) in enumerate(paths):
        probe_task(fp, axes[0][2 * i], axes[0][2 * i + 1], t)
    fig.tight_layout()
    fig.savefig(args.out)
    print("Wrote", args.out)


if __name__ == "__main__":
    main()
