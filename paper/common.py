"""Shared loaders for the paper analysis scripts.

The reference's figure/table scripts bypass MLflow and issue raw SQL over
the sqlite schema, joining metrics x runs x experiments x tags and keeping
child runs only (reference ``paper/tab1.py:28-51``, ``paper/fig1.py:31-53``).
The tracking store here implements the same schema, so the same join works
verbatim; this module centralizes it plus the method-name canonicalization
every script repeats.
"""

from __future__ import annotations

import os
import sqlite3

import pandas as pd

# canonical CODA config used in every reference figure (paper/tab1.py:60)
CODA_NAME = "coda-lr=0.01-mult=2.0-no-prefilter"

METHOD_LABELS = {
    "activetesting": "Active Testing",
    "iid": "Random Sampling",
    "model_picker": "ModelSelector",
    "uncertainty": "Uncertainty",
    "vma": "VMA",
}

GLOBAL_METHODS = ["Random Sampling", "Uncertainty", "Active Testing", "VMA",
                  "ModelSelector", "CODA (Ours)"]

# the reference's 26-task benchmark grouping (paper/tab1.py:113-121)
TASK_GROUPS = {
    "DomainNet126": [
        "real_sketch", "real_painting", "real_clipart",
        "sketch_real", "sketch_painting", "sketch_clipart",
        "painting_real", "painting_sketch", "painting_clipart",
        "clipart_real", "clipart_sketch", "clipart_painting",
    ],
    "WILDS": ["iwildcam", "camelyon", "fmow", "civilcomments"],
    "MSV": ["cifar10_4070", "cifar10_5592", "pacs"],
    "GLUE": ["glue/cola", "glue/mnli", "glue/qnli", "glue/qqp", "glue/rte",
             "glue/sst2"],
}

_SQL = """
SELECT  e.name   AS task,
        rn.value AS run_name,
        m.value  AS value,
        m.step   AS step
FROM    metrics   m
JOIN    runs      r   ON m.run_uuid      = r.run_uuid
JOIN    experiments e ON r.experiment_id = e.experiment_id
JOIN    tags t_parent
       ON r.run_uuid = t_parent.run_uuid
      AND t_parent.key = 'mlflow.parentRunId'
LEFT JOIN tags rn
       ON r.run_uuid = rn.run_uuid
      AND rn.key     = 'mlflow.runName'
WHERE   m.key  = ?
  AND   m.is_nan = 0
  AND   r.lifecycle_stage = 'active'
  AND   e.lifecycle_stage = 'active'
"""


def extract_method_from_run_name(run_name: str) -> str:
    """``<task>-<method>-<seed>`` -> ``<method>`` (reference fig1.py:24-29)."""
    parts = run_name.split("-")
    if len(parts) >= 2 and parts[-1].isdigit():
        parts = parts[:-1]
    return "-".join(parts[1:]) if len(parts) > 1 else run_name


def load_metric(db_path: str, metric: str, coda_name: str = CODA_NAME,
                step: int | None = None) -> pd.DataFrame:
    """Child-run metric rows with canonical method labels, x100 like the
    paper. Columns: task, method, step, value (seed-mean), std."""
    if not os.path.exists(db_path):
        raise FileNotFoundError(f"Tracking DB not found: {db_path}")
    with sqlite3.connect(db_path) as conn:
        sql, params = _SQL, [metric]
        if step is not None:
            sql += "  AND m.step = ?"
            params.append(step)
        df = pd.read_sql_query(sql, conn, params=params)
    if df.empty:
        return df.assign(method=[])
    df["method"] = df["run_name"].apply(extract_method_from_run_name)
    # keep baselines + the one canonical coda config; a bare "coda" run IS
    # the canonical config (those are the CLI defaults), so accept it too
    canonical = {coda_name, "coda"}
    df = df[(~df.method.str.contains("coda")) | df.method.isin(canonical)]
    df["method"] = df["method"].map(
        lambda m: "CODA (Ours)" if m in canonical
        else METHOD_LABELS.get(m, m))
    g = df.groupby(["task", "method", "step"], as_index=False)["value"]
    mean = g.mean()
    mean["std"] = g.std()["value"].fillna(0.0)
    mean["value"] *= 100
    mean["std"] *= 100
    return mean


def tasks_in(df: pd.DataFrame, preferred_order=None) -> list[str]:
    present = list(df.task.unique())
    if preferred_order:
        ordered = [t for t in preferred_order if t in present]
        return ordered + sorted(set(present) - set(ordered))
    return sorted(present)
