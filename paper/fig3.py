"""Figure 3: cumulative-regret curves per task group, with per-task
prediction-tensor memory footprints (capability parity with reference
``paper/fig3.py``: same groups and the same hard-coded per-task fp32 GB
table; groups with no data in the DB are skipped).

Usage: python paper/fig3.py [--db coda.sqlite] [--out fig3.pdf]
"""

from __future__ import annotations

import argparse

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import seaborn as sns

from common import CODA_NAME, GLOBAL_METHODS, load_metric

# fp32 (H, N, C) bytes per task (reference paper/fig3.py:129-193)
MEMORY_USE_GB = {
    "MSV\n7-10 class": {
        "cifar10_4070": 0.04063744,
        "cifar10_5592": 0.04063744,
        "pacs": 0.016964096,
    },
    "GLUE\n2-3 class": {
        "glue/cola": 0.009445376,
        "glue/mnli": 0.018265088,
        "glue/qnli": 0.012504064,
        "glue/qqp": 0.042404864,
        "glue/rte": 0.00872192,
        "glue/sst2": 0.00921088,
        "glue/mrpc": 0.008840192,
    },
    "WILDS Multiclass\n62-182 class": {
        "fmow": 1.32826112,
        "iwildcam": 1.510516736,
    },
    "WILDS Binary\n2-class": {
        "civilcomments": 0.031593984,
        "camelyon": 0.036469248,
    },
    "DomainNet\n126-class": {
        "real_sketch": 3.758885376,
        "real_clipart": 2.900022784,
        "real_painting": 1.628145152,
        "sketch_real": 9.98845184,
        "sketch_clipart": 2.900022784,
        "sketch_painting": 1.628145152,
        "clipart_real": 6.378751488,
        "clipart_sketch": 3.232947712,
        "clipart_painting": 1.628145152,
        "painting_real": 9.98845184,
        "painting_sketch": 3.157962752,
        "painting_clipart": 2.900022784,
    },
}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--db", default="coda.sqlite")
    p.add_argument("--metric", default="cumulative regret")
    p.add_argument("--coda-name", default=CODA_NAME)
    p.add_argument("--out", default="fig3.pdf")
    args = p.parse_args(argv)

    df = load_metric(args.db, args.metric, coda_name=args.coda_name)
    if df.empty:
        raise SystemExit(f"No '{args.metric}' rows in {args.db}")
    methods = [m for m in GLOBAL_METHODS if m in set(df.method)]
    present = set(df.task)
    groups = {g: [t for t in ts if t in present]
              for g, ts in MEMORY_USE_GB.items()}
    groups = {g: ts for g, ts in groups.items() if ts}
    other = sorted(present - {t for ts in groups.values() for t in ts})
    if other:
        groups["Other"] = other
    if not groups:
        raise SystemExit("No known tasks in the DB")

    palette = sns.color_palette("colorblind", n_colors=len(methods))
    colors = dict(zip(methods, palette[::-1]))
    fig, axes = plt.subplots(1, len(groups),
                             figsize=(3.2 * len(groups), 3), squeeze=False)
    for ax, (g_name, g_tasks) in zip(axes[0], groups.items()):
        sub = df[df.task.isin(g_tasks)]
        # group curve = mean over the group's tasks of seed-mean regret
        for m in methods:
            curve = (sub[sub.method == m].groupby("step")["value"]
                     .mean().sort_index())
            if curve.empty:
                continue
            lw = 2.5 if m.startswith("CODA") else 1.5
            ax.plot(curve.index, curve.values, label=m,
                    color=colors[m], linewidth=lw)
        mem = MEMORY_USE_GB.get(g_name, {})
        gb = sum(mem.get(t, 0.0) for t in g_tasks)
        title = g_name + (f"\n{gb:.2f} GB" if gb else "")
        ax.set_title(title, fontsize=9)
        ax.set_xlabel("Number of labels")
    axes[0][0].set_ylabel(f"{args.metric} (x100)")
    axes[0][0].legend(fontsize=7)
    fig.tight_layout()
    fig.savefig(args.out)
    print("Wrote", args.out)


if __name__ == "__main__":
    main()
