"""Human-in-the-loop CODA demo: the user is the oracle.

Capability parity with the reference Gradio app (reference ``demo/app.py``):
pick the next most-informative item (``get_next_coda_image``,
``demo/app.py:137-172``), let a human label it with one of the class buttons
or skip with "I don't know" — which removes the point from the pool without
updating beliefs (``demo/app.py:186-189``) — and show live charts of CODA's
P(best) per model next to the models' true accuracies
(``demo/app.py:212-301``). Deliberately wrong answers are allowed and, as in
the reference, "may mislead the model selection process" (``demo/app.py:195``).

Re-architected for this framework:

  * no Gradio (not in the image): a dependency-free ``http.server`` JSON API
    plus one self-contained HTML page with inline SVG charts;
  * selector state is the pure-functional CODA state behind an
    ``InteractiveSelector`` (the one consumer that genuinely needs a
    host-driven incremental ``step()`` — SURVEY.md §7.6), jit-compiled once
    at session start, so each click is a few compiled device calls;
  * sessions are isolated objects keyed by a token — the reference keeps one
    process-global session (``demo/app.py:86-92``).

Run:  python demo/app.py [--task TASK --data-dir data] [--port 7860]
Without a task file it falls back to a seeded synthetic pool so the demo
always works offline.

This app serves ONE selector session per user, one device round trip per
click. For many concurrent sessions multiplexed onto one accelerator —
micro-batched so each tick is a single compiled step over every active
session — use the serving layer: ``python -m coda_tpu.cli serve``
(``coda_tpu/serve/``, ARCHITECTURE.md §6).
"""

from __future__ import annotations

import argparse
import json
import os
import secrets
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

# direct script execution (`python demo/app.py`) puts demo/ on sys.path, not
# the repo root — make `coda_tpu` / `demo.*` importable either way
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


# ----------------------------------------------------------------------------
# session: one human-in-the-loop experiment
# ----------------------------------------------------------------------------

class DemoSession:
    """One interactive CODA run over a (H, N, C) prediction pool."""

    def __init__(self, preds, labels, class_names=None, model_names=None,
                 seed: int = 0, image_paths=None):
        import jax.numpy as jnp

        from coda_tpu.oracle import true_losses
        from coda_tpu.selectors import CODAHyperparams, make_coda
        from coda_tpu.selectors.protocol import InteractiveSelector

        self.preds = np.asarray(preds, np.float32)
        self.labels = None if labels is None else np.asarray(labels)
        H, N, C = self.preds.shape
        # one path per item (index order = npz order); None for tensor-only
        # tasks, which fall back to the prediction table
        if image_paths is not None and len(image_paths) != N:
            raise ValueError(
                f"got {len(image_paths)} image paths for {N} items")
        self.image_paths = None if image_paths is None else list(image_paths)
        self.class_names = list(class_names or [f"class {c}" for c in range(C)])
        self.model_names = list(model_names or [f"model {h}" for h in range(H)])
        # demo hyperparams follow the reference's Args stub (demo/app.py:70-81)
        self.selector = InteractiveSelector(
            make_coda(jnp.asarray(self.preds), CODAHyperparams()), seed=seed
        )
        self.true_accs = (
            None
            if self.labels is None
            else (1.0 - np.asarray(
                true_losses(jnp.asarray(self.preds), jnp.asarray(self.labels))
            )).tolist()
        )
        self.step = 0
        self.skipped: list[int] = []
        self.current_idx: int | None = None
        self.current_prob = 0.0
        # reentrant: answer() holds it across its next_item()/state() calls
        self.lock = threading.RLock()
        # compile once at session start; clicks reuse the executable
        import jax

        self._get_pbest = jax.jit(self.selector.selector.extras["get_pbest"])

    # -- the reference's get_next_coda_image (demo/app.py:137-172) -----------
    def next_item(self) -> dict:
        with self.lock:
            idx, prob = self.selector.get_next_item_to_label()
            self.current_idx, self.current_prob = idx, prob
            return self.state()

    # -- the reference's check_answer (demo/app.py:174-210) ------------------
    def answer(self, label) -> dict:
        with self.lock:
            idx = self.current_idx
            if idx is None:
                return self.state()
            if label == "skip":
                # "I don't know": drop the point, no belief update
                # (reference demo/app.py:186-189)
                self.selector.state = self.selector.state._replace(
                    unlabeled=self.selector.state.unlabeled.at[idx].set(False)
                )
                self.skipped.append(idx)
            else:
                label = int(label)  # ValueError/TypeError -> HTTP 400
                if not 0 <= label < len(self.class_names):
                    raise ValueError(f"label {label} out of range")
                self.selector.add_label(idx, label, self.current_prob)
            self.step += 1
            self.current_idx = None
            return self.next_item()

    def state(self) -> dict:
        with self.lock:
            pbest = np.asarray(self._get_pbest(self.selector.state))
            idx = self.current_idx
            item_preds = (
                None if idx is None else self.preds[:, idx, :].tolist()
            )
            true_label = (
                None
                if (self.labels is None or idx is None)
                else int(self.labels[idx])
            )
            return {
                "step": self.step,
                "idx": idx,
                "has_images": self.image_paths is not None,
                "item_preds": item_preds,
                "true_label": true_label,
                "class_names": self.class_names,
                "model_names": self.model_names,
                "pbest": pbest.tolist(),
                "true_accs": self.true_accs,
                "best_model": int(np.argmax(pbest)),
                "n_labeled": len(self.selector.labeled_idxs),
                "n_skipped": len(self.skipped),
            }


# ----------------------------------------------------------------------------
# HTTP server
# ----------------------------------------------------------------------------

# Bounded session table: each session holds a full host + device copy of the
# prediction pool, so unbounded growth (one /api/start per page load) would
# OOM on large pools. Oldest sessions are evicted FIFO past the cap — still
# an upgrade over the reference's single process-global session
# (reference demo/app.py:86-92).
MAX_SESSIONS = 8
_SESSIONS: dict[str, DemoSession] = {}  # insertion-ordered
_SESSIONS_LOCK = threading.Lock()  # guards insert/evict/lookup
_FACTORY = None  # () -> DemoSession


PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>CODA demo</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;max-width:70rem}
 button{margin:.2rem;padding:.5rem 1rem;font-size:1rem;cursor:pointer}
 .cols{display:flex;gap:2rem;flex-wrap:wrap}
 .card{border:1px solid #ccc;border-radius:8px;padding:1rem;flex:1;min-width:20rem}
 .bar{fill:#4a7dbd}.bar.best{fill:#d97706}.truebar{fill:#999}
 td,th{padding:.15rem .5rem;text-align:right;font-variant-numeric:tabular-nums}
 #status{color:#666}
</style></head><body>
<h2>CODA: consensus-driven active model selection — you are the oracle</h2>
<p id="status">starting…</p>
<div class="cols">
 <div class="card"><h3>Label this item</h3>
  <p>Item <span id="idx">—</span>. Which class is it?
     (the true class is hidden; answer honestly — or don't, and watch CODA cope)</p>
  <img id="itemimg" alt="item being labeled"
       style="display:none;max-width:100%;max-height:320px;border-radius:6px;
              border:1px solid #ccc;margin-bottom:.5rem">
  <div id="buttons"></div>
  <h4>Per-model predictions for this item</h4>
  <div id="preds"></div></div>
 <div class="card"><h3>CODA's belief: P(model is best)</h3>
  <svg id="pbest" width="420" height="240"></svg>
  <h3>True accuracy (hidden from CODA)</h3>
  <svg id="accs" width="420" height="240"></svg></div>
</div>
<script>
let token=null;
async function api(path,body){
 const r=await fetch(path,{method:body?"POST":"GET",
   headers:{"Content-Type":"application/json"},
   body:body?JSON.stringify(body):undefined});
 return r.json();}
function bars(svgId,vals,names,best){
 const svg=document.getElementById(svgId);const W=420,H=240,m=4;
 const bw=(H-20)/vals.length; const mx=Math.max(...vals,1e-9);
 svg.innerHTML=vals.map((v,i)=>{
  const w=(W-150)*v/mx;
  return `<rect class="bar${i===best?' best':''}" x="130" y="${10+i*bw}" width="${w}" height="${bw-m}"></rect>`+
   `<text x="125" y="${10+i*bw+bw/2}" text-anchor="end" font-size="11">${names[i]}</text>`+
   `<text x="${135+w}" y="${10+i*bw+bw/2}" font-size="11">${v.toFixed(3)}</text>`;
 }).join("");}
function render(s){
 document.getElementById("status").textContent=
  `step ${s.step} — ${s.n_labeled} labeled, ${s.n_skipped} skipped — `+
  `CODA's current pick: ${s.model_names[s.best_model]}`;
 document.getElementById("idx").textContent=s.idx;
 const img=document.getElementById("itemimg");
 if(s.has_images&&s.idx!==null){
  img.src=`/api/image?token=${token}&idx=${s.idx}`;img.style.display="block";
 }else{img.style.display="none";}
 const bt=document.getElementById("buttons");
 bt.innerHTML=s.class_names.map((c,i)=>
   `<button onclick="answer(${i})">${c}</button>`).join("")+
   `<button onclick="answer('skip')" style="background:#eee">I don't know</button>`;
 if(s.item_preds){
  const rows=s.model_names.map((m,h)=>`<tr><th>${m}</th>`+
    s.item_preds[h].map(p=>`<td>${p.toFixed(2)}</td>`).join("")+`</tr>`);
  document.getElementById("preds").innerHTML=
   `<table><tr><th></th>${s.class_names.map(c=>`<th>${c}</th>`).join("")}</tr>`+
   rows.join("")+`</table>`;}
 bars("pbest",s.pbest,s.model_names,s.best_model);
 if(s.true_accs) bars("accs",s.true_accs,s.model_names,
   s.true_accs.indexOf(Math.max(...s.true_accs)));}
async function answer(l){render(await api("/api/answer",{token,label:l}));}
(async()=>{const s=await api("/api/start",{});token=s.token;render(s.state);})();
</script></body></html>
"""


class Handler(BaseHTTPRequestHandler):
    def _json(self, obj, code: int = 200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass

    def do_GET(self):
        if self.path in ("/", "/index.html"):
            body = PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.startswith("/api/image"):
            self._serve_image()
        else:
            self._json({"error": "not found"}, 404)

    def _serve_image(self):
        """GET /api/image?token=T&idx=I -> the item's image bytes.

        Only paths from the session's own ``image_paths`` list are ever
        opened (idx is range-checked), so no request-controlled path
        touches the filesystem."""
        import mimetypes
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)
        with _SESSIONS_LOCK:
            sess = _SESSIONS.get((q.get("token") or [""])[0])
        if sess is None or sess.image_paths is None:
            return self._json({"error": "no images for this session"}, 404)
        try:
            idx = int((q.get("idx") or [""])[0])
        except ValueError:
            return self._json({"error": "bad idx"}, 400)
        if not 0 <= idx < len(sess.image_paths):
            return self._json({"error": "idx out of range"}, 400)
        path = sess.image_paths[idx]
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return self._json({"error": "image unavailable"}, 404)
        ctype = mimetypes.guess_type(path)[0] or "application/octet-stream"
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_POST(self):
        try:
            self._do_post()
        except (ValueError, TypeError, KeyError) as e:
            # malformed JSON / non-integer label / missing field -> 400,
            # never a dropped connection
            self._json({"error": f"bad request: {e}"}, 400)

    def _do_post(self):
        n = int(self.headers.get("Content-Length", 0))
        req = json.loads(self.rfile.read(n) or b"{}")
        if self.path == "/api/start":
            sess = _FACTORY()
            token = secrets.token_hex(8)
            with _SESSIONS_LOCK:
                _SESSIONS[token] = sess
                while len(_SESSIONS) > MAX_SESSIONS:
                    _SESSIONS.pop(next(iter(_SESSIONS)))
            self._json({"token": token, "state": sess.next_item()})
        elif self.path == "/api/answer":
            with _SESSIONS_LOCK:
                sess = _SESSIONS.get(req.get("token", ""))
            if sess is None:
                self._json({"error": "unknown session"}, 400)
            else:
                self._json(sess.answer(req.get("label")))
        elif self.path == "/api/state":
            with _SESSIONS_LOCK:
                sess = _SESSIONS.get(req.get("token", ""))
            if sess is None:
                self._json({"error": "unknown session"}, 400)
            else:
                self._json(sess.state())
        else:
            self._json({"error": "not found"}, 404)


def make_server(factory, port: int = 0) -> ThreadingHTTPServer:
    """Build the HTTP server; ``port=0`` picks a free port (for tests)."""
    global _FACTORY
    _FACTORY = factory
    return ThreadingHTTPServer(("127.0.0.1", port), Handler)


def resolve_image_paths(ds, images_dir):
    """Per-item image paths for a loaded dataset, or None (table fallback).

    Preferred source: the ``filenames`` the pool builder records in the npz
    (index order is authoritative), joined onto ``--images-dir``. Without
    recorded filenames, the sorted directory listing is used — the same
    ordering contract ``hf_zeroshot.list_images`` built the tensor with.
    """
    if images_dir is None:
        return None
    N = ds.preds.shape[1]
    if ds.filenames is not None:
        return [os.path.join(images_dir, f) for f in ds.filenames]
    from demo.hf_zeroshot import list_images

    paths = list_images(images_dir)
    if len(paths) != N:
        raise SystemExit(
            f"--images-dir has {len(paths)} images but the task has {N} "
            "items; rebuild the pool or pass the matching directory")
    return paths


def default_factory(args):
    def factory() -> DemoSession:
        from coda_tpu.cli import load_dataset

        if args.task or args.synthetic:
            ds = load_dataset(args)
            return DemoSession(
                ds.preds, ds.labels,
                class_names=ds.class_names,
                image_paths=resolve_image_paths(
                    ds, getattr(args, "images_dir", None)),
            )
        # no task requested: prefer the committed REAL pool (CLIP
        # checkpoints scored over the NIST digit scans) with its images —
        # the out-of-the-box demo is then the reference's experience
        # (real images + a 3-model zero-shot pool) with zero setup
        here = os.path.dirname(os.path.abspath(__file__))
        real_pool = os.path.join(here, "..", "data", "digits_clip.npz")
        real_imgs = os.path.join(here, "digit_images")
        if os.path.exists(real_pool) and os.path.isdir(real_imgs):
            from coda_tpu.data import Dataset

            ds = Dataset.from_file(real_pool)
            return DemoSession(
                ds.preds, ds.labels,
                class_names=[f"digit {c}" for c in ds.class_names],
                model_names=["tiny-clip-a", "tiny-clip-b",
                             "tiny-clip-under"],
                image_paths=resolve_image_paths(ds, real_imgs),
            )
        # offline fallback: small seeded pool, 3 models x 5 classes like the
        # reference's iWildCam subset (demo/app.py README)
        from coda_tpu.data import make_synthetic_task

        task = make_synthetic_task(seed=0, H=3, N=200, C=5)
        return DemoSession(
            task.preds, task.labels,
            class_names=[f"species {c}" for c in range(5)],
            model_names=["clip-vit-l", "siglip2", "bioclip"],
        )

    return factory


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--task", default=None)
    p.add_argument("--data-dir", default="data")
    p.add_argument("--synthetic", default=None)
    p.add_argument("--images-dir", default=None,
                   help="directory with the task's source images; the page "
                        "then shows the item being labeled (reference "
                        "demo/app.py:137-172)")
    p.add_argument("--port", type=int, default=7860)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (cpu/tpu) — same as main.py; "
                        "env JAX_PLATFORMS alone is overridden by site "
                        "hooks that force-register an accelerator")
    args = p.parse_args(argv)

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)

    srv = make_server(default_factory(args), args.port)
    print(f"CODA demo on http://127.0.0.1:{srv.server_address[1]}/")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
