"""Build a candidate model pool: zero-shot predictions -> (H, N, C) tensor.

Capability parity with the reference pool builder (reference
``demo/hf_zeroshot.py``): run several zero-shot image classifiers over an
image folder, write one JSON of per-image class scores per model with
skip-if-exists resume (``demo/hf_zeroshot.py:244-246``), degrade to a uniform
distribution when a model fails on an image (``:108-110,162``), then assemble
all model outputs into the dense prediction tensor the selectors consume.

TPU-first differences from the reference:

  * model backends are a small registry of callables instead of three
    hard-coded branches (CLIP via the generic transformers pipeline
    ``:170-219``, SigLIP via manual processor+softmax ``:118-168``, BioCLIP
    via pybioclip ``:71-116``); backends whose libraries are missing are
    *gated*, not errors, so the builder runs in this image (transformers is
    present; pybioclip/open_clip are not);
  * the assembled pool is saved as ``<task>.npz`` (preds + labels), the
    native format of ``coda_tpu.data.Dataset`` — host-side IO stays NumPy,
    device work stays in the selectors;
  * ``build_pool`` accepts injected scorer callables, so tests exercise the
    full resume/fallback/assembly logic offline with fake models.

CLI:  python demo/hf_zeroshot.py --images-dir D --classes a b c --out task
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Callable, Sequence

import numpy as np

# ---------------------------------------------------------------------------
# model registry: name -> factory returning score_image(path, classes) -> list
# ---------------------------------------------------------------------------

# the reference's candidate pool (demo/hf_zeroshot.py:46-50)
DEFAULT_MODELS = [
    "openai/clip-vit-large-patch14",
    "google/siglip2-base-patch16-224",
    "imageomics/bioclip",
]


def _hf_pipeline_scorer(model_name: str) -> Callable:
    """Generic transformers zero-shot pipeline (reference ``:170-219``).

    Handles both CLIP-style and SigLIP-style checkpoints; transformers picks
    the right processor. Raises ImportError when transformers is missing.
    """
    from transformers import pipeline

    pipe = pipeline("zero-shot-image-classification", model=model_name)

    def score(image_path: str, classes: Sequence[str]) -> list[float]:
        out = pipe(image_path, candidate_labels=list(classes))
        by_label = {o["label"]: float(o["score"]) for o in out}
        scores = np.array([by_label.get(c, 0.0) for c in classes], np.float64)
        total = scores.sum()
        return (scores / total if total > 0 else
                np.full(len(classes), 1.0 / len(classes))).tolist()

    return score


def _manual_processor_scorer(model_name: str) -> Callable:
    """Manual processor -> model -> softmax path (reference ``:118-168`` —
    its SigLIP branch bypasses the generic pipeline and drives the
    processor/model directly). Used for SigLIP-family checkpoints, and
    exercisable against ANY dual-encoder checkpoint via
    ``make_scorer(..., backend='manual')`` — the committed locally-trained
    CLIP runs through it in tests, proving the non-pipeline path without
    hub access. Same hypothesis template as the pipeline backend so the
    two produce comparable scores.
    """
    import torch
    from transformers import AutoModel, AutoProcessor

    model = AutoModel.from_pretrained(model_name)
    processor = AutoProcessor.from_pretrained(model_name)
    model.eval()

    def score(image_path: str, classes: Sequence[str]) -> list[float]:
        from PIL import Image

        img = Image.open(image_path).convert("RGB")
        prompts = [f"This is a photo of {c}." for c in classes]
        inputs = processor(text=prompts, images=img, return_tensors="pt",
                           padding=True)
        with torch.no_grad():
            logits = model(**inputs).logits_per_image[0]
        probs = torch.softmax(logits.float(), dim=-1)
        return [float(p) for p in probs]

    return score


def _bioclip_scorer(model_name: str) -> Callable:
    """BioCLIP via pybioclip (reference ``:71-116``); gated on the import."""
    from bioclip import CustomLabelsClassifier  # not in this image: gated

    clf_cache: dict[tuple, object] = {}

    def score(image_path: str, classes: Sequence[str]) -> list[float]:
        # build the classifier once per class list, not once per image
        key = tuple(classes)
        if key not in clf_cache:
            clf_cache[key] = CustomLabelsClassifier(list(classes))
        out = clf_cache[key].predict(image_path)
        by_label = {o["classification"]: float(o["score"]) for o in out}
        return [by_label.get(c, 0.0) for c in classes]

    return score


def make_scorer(model_name: str, backend: str | None = None) -> Callable:
    """``backend``: None (infer from the name — bioclip -> pybioclip,
    siglip -> manual processor, else pipeline) | 'pipeline' | 'manual' |
    'bioclip'."""
    name = model_name.lower()
    if backend == "bioclip" or (backend is None and "bioclip" in name):
        return _bioclip_scorer(model_name)
    if backend == "manual" or (backend is None and "siglip" in name):
        return _manual_processor_scorer(model_name)
    if backend not in (None, "pipeline"):
        raise ValueError(f"unknown scorer backend {backend!r} "
                         "(use pipeline/manual/bioclip)")
    return _hf_pipeline_scorer(model_name)


# ---------------------------------------------------------------------------
# pool building
# ---------------------------------------------------------------------------

IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".webp", ".bmp")


def list_images(images_dir: str) -> list[str]:
    return sorted(
        os.path.join(images_dir, f)
        for f in os.listdir(images_dir)
        if f.lower().endswith(IMAGE_EXTS)
    )


def run_model(
    model_name: str,
    images: Sequence[str],
    classes: Sequence[str],
    out_dir: str,
    scorer: Callable | None = None,
) -> str:
    """Score every image with one model -> ``<out_dir>/<model>.json``.

    Resumes by skipping models whose output file already exists (reference
    ``demo/hf_zeroshot.py:244-246``); falls back to a uniform distribution
    for images the model fails on (``:108-110,162``).
    """
    import hashlib

    os.makedirs(out_dir, exist_ok=True)
    # resume key includes the class list: rerunning with different classes
    # must re-score, not silently reuse stale per-model JSON
    class_tag = hashlib.sha1(
        "\x00".join(classes).encode()
    ).hexdigest()[:8]
    out_path = os.path.join(
        out_dir, f"{model_name.replace('/', '__')}_{class_tag}.json"
    )
    if os.path.exists(out_path):
        return out_path

    if scorer is None:
        scorer = make_scorer(model_name)
    uniform = [1.0 / len(classes)] * len(classes)
    results = {}
    for img in images:
        try:
            results[os.path.basename(img)] = scorer(img, classes)
        except Exception as e:  # per-image failure -> uniform (reference)
            print(f"[pool] {model_name} failed on {img}: {e}")
            results[os.path.basename(img)] = uniform

    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"model": model_name, "classes": list(classes),
                   "scores": results}, f)
    os.replace(tmp, out_path)
    return out_path


def assemble_pool(
    json_paths: Sequence[str],
    images: Sequence[str],
    classes: Sequence[str],
    out_path: str,
    labels: Sequence[int] | None = None,
) -> np.ndarray:
    """Stack per-model JSONs into the dense fp32 ``(H, N, C)`` tensor and
    save it (plus optional labels) as ``.npz`` for ``Dataset.from_file``.

    The item filenames and class names are recorded alongside the tensor so
    downstream consumers (the human-in-the-loop demo) can show the actual
    image being labeled (the reference's demo loop, reference
    ``demo/app.py:137-172``) — index order in the npz IS the filename order.
    """
    H, N, C = len(json_paths), len(images), len(classes)
    if labels is not None and len(labels) != N:
        raise ValueError(
            f"labels length {len(labels)} != {N} images — a mismatched "
            "labels file would silently corrupt oracle accuracy downstream"
        )
    preds = np.full((H, N, C), 1.0 / C, np.float32)
    names = [os.path.basename(p) for p in images]
    for h, jp in enumerate(json_paths):
        with open(jp) as f:
            data = json.load(f)
        if data["classes"] != list(classes):
            raise ValueError(f"{jp}: class list mismatch vs pool")
        for n, name in enumerate(names):
            if name in data["scores"]:
                preds[h, n] = np.asarray(data["scores"][name], np.float32)
    out = {"preds": preds,
           "filenames": np.asarray(names),
           "classes": np.asarray(list(classes))}
    if labels is not None:
        out["labels"] = np.asarray(labels, np.int64)
    np.savez(out_path, **out)
    return preds


def build_pool(
    images_dir: str,
    classes: Sequence[str],
    out: str,
    models: Sequence[str] = tuple(DEFAULT_MODELS),
    scorers: dict[str, Callable] | None = None,
    labels: Sequence[int] | None = None,
    results_dir: str | None = None,
) -> np.ndarray:
    """End-to-end: score all models (resumable), assemble, save ``<out>.npz``.

    Models whose backend libraries are unavailable are skipped with a notice
    rather than failing the build — the pool is whatever subset ran.
    """
    images = list_images(images_dir)
    if not images:
        raise ValueError(f"no images found under {images_dir}")
    results_dir = results_dir or (out + "_results")
    json_paths = []
    for m in models:
        try:
            scorer = (scorers or {}).get(m)
            json_paths.append(run_model(m, images, classes, results_dir,
                                        scorer=scorer))
        except ImportError as e:
            print(f"[pool] skipping {m}: backend unavailable ({e})")
    if not json_paths:
        raise RuntimeError("no model backend available; nothing scored")
    return assemble_pool(json_paths, images, classes, out + ".npz",
                         labels=labels)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--images-dir", required=True)
    p.add_argument("--classes", nargs="+", required=True)
    p.add_argument("--out", required=True,
                   help="output task path (writes <out>.npz)")
    p.add_argument("--models", nargs="+", default=DEFAULT_MODELS)
    p.add_argument("--labels", default=None,
                   help="optional .npy of int labels in image (sorted-"
                        "filename) order; stored in the npz so the task "
                        "runs under the benchmark oracle")
    args = p.parse_args(argv)
    labels = np.load(args.labels) if args.labels else None
    preds = build_pool(args.images_dir, args.classes, args.out,
                       models=args.models, labels=labels)
    print(f"pool shape {preds.shape} -> {args.out}.npz")


if __name__ == "__main__":
    main()
