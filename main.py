"""Benchmark entry point — same CLI surface as the reference's ``main.py``.

Usage: ``python main.py --task cifar10_5592 --method coda`` (or
``--synthetic H,N,C`` for a seeded synthetic task). See ``coda_tpu/cli.py``.
"""

from coda_tpu.cli import main

if __name__ == "__main__":
    main()
