"""Benchmark: CODA selection-steps/sec on the current accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The headline config follows BASELINE.json (selection-steps/sec at M=1k
models, N=50k points); ``--small`` runs a reduced config for smoke tests.
``vs_baseline`` compares against the PyTorch reference implementation's
measured per-step wall-clock on this machine's CPU (the reference has no
published speed numbers — see BASELINE.md). The reference timing is cached
in ``bench_baseline.json`` after the first measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BASELINE_CACHE = os.path.join(os.path.dirname(__file__), "bench_baseline.json")


def bench_ours(H: int, N: int, C: int, iters: int, eig_chunk: int) -> float:
    """Returns selection steps/sec for a compiled CODA experiment."""
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.loop import build_experiment_fn
    from coda_tpu.oracle import true_losses
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = make_synthetic_task(seed=0, H=H, N=N, C=C)
    hp = CODAHyperparams(eig_chunk=eig_chunk)

    # Build the selector INSIDE the jitted function so the (H, N, C) tensor
    # is a traced argument, not a baked-in constant (2 GB of captured
    # constants at M=1k, N=50k would bloat lowering and HBM).
    def run(preds, labels, key):
        sel = make_coda(preds, hp)
        losses = true_losses(preds, labels)
        return build_experiment_fn(sel, labels, losses, iters=iters)(key)

    import numpy as np

    fn = jax.jit(run)
    # jit ONCE; warm-up hits the same compiled executable as the measurement.
    # Time through a host read of the result: on the experimental axon TPU
    # tunnel, block_until_ready alone can return before the queue flushes.
    np.asarray(fn(task.preds, task.labels, jax.random.PRNGKey(0)).regret)
    t0 = time.perf_counter()
    np.asarray(fn(task.preds, task.labels, jax.random.PRNGKey(1)).regret)
    wall = time.perf_counter() - t0
    return iters / wall


REF_MAX_H = 100
REF_MAX_N = 5000


def measure_reference_baseline(H: int, N: int, C: int, steps: int = 2) -> float:
    """Steps/sec of the PyTorch reference (CPU) on the same synthetic task.

    Imports the read-only reference checkout if available; returns 0.0 when
    it isn't (vs_baseline is then reported as 0.0 = unknown).

    At the headline scale (M=1000, N=50000) one reference step takes hours
    on CPU (its per-step cost is ~linear in H*N), so the reference is timed
    at a feasible size (H<=100, N<=5000) and extrapolated linearly in H*N —
    reported as an estimate in favor of the reference (its Python-loop
    overhead grows superlinearly in practice).
    """
    ref_path = "/root/reference"
    if not os.path.isdir(ref_path):
        return 0.0
    sys.path.insert(0, ref_path)
    try:
        import numpy as np
        import torch

        from coda.coda import CODA as RefCODA  # reference package

        from coda_tpu.data import make_synthetic_task

        Hm, Nm = min(H, REF_MAX_H), min(N, REF_MAX_N)
        scale = (Hm * Nm) / (H * N)  # <=1; reference steps/sec at full size
        task = make_synthetic_task(seed=0, H=Hm, N=Nm, C=C)

        class _DS:
            preds = torch.from_numpy(np.asarray(task.preds)).float()
            labels = torch.from_numpy(np.asarray(task.labels))

        sel = RefCODA(_DS())
        labels = np.asarray(task.labels)
        t0 = time.perf_counter()
        for _ in range(steps):
            idx, prob = sel.get_next_item_to_label()
            sel.add_label(int(idx), int(labels[int(idx)]), prob)
            sel.get_best_model_prediction()
        wall = time.perf_counter() - t0
        return (steps / wall) * scale
    except Exception as e:  # pragma: no cover
        print(f"[bench] reference baseline unavailable: {e}", file=sys.stderr)
        return 0.0
    finally:
        sys.path.remove(ref_path)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="small smoke config instead of the headline M=1k,N=50k")
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--skip-reference", action="store_true")
    args = ap.parse_args()

    if args.small:
        H, N, C, iters, chunk = 32, 2000, 10, 10, 1000
    else:
        H, N, C, iters, chunk = 1000, 50_000, 10, 20, 2048

    steps_per_sec = bench_ours(H, N, C, iters=args.iters or iters,
                               eig_chunk=chunk)

    cache_key = f"ref_steps_per_sec_h{H}_n{N}_c{C}"
    baseline = 0.0
    cache = {}
    if os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            cache = json.load(f)
        baseline = cache.get(cache_key, 0.0)
    if baseline == 0.0 and not args.skip_reference:
        baseline = measure_reference_baseline(H, N, C)
        if baseline > 0.0:
            cache[cache_key] = baseline
            with open(BASELINE_CACHE, "w") as f:
                json.dump(cache, f, indent=2)

    vs = steps_per_sec / baseline if baseline > 0 else 0.0
    print(json.dumps({
        "metric": f"coda-selection-steps/sec (M={H}, N={N}, C={C})",
        "value": round(steps_per_sec, 4),
        "unit": "steps/sec",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
