"""Benchmark: CODA selection-steps/sec on the current accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Timing protocol (designed so the number survives independent re-timing):

  * every timed run materializes the FULL result tree on the host
    (``jax.tree.map(np.asarray, ...)``) — nothing is timed through a bare
    ``block_until_ready`` that an experimental device tunnel can satisfy
    before the compute queue drains;
  * the reported value is the median of ``--reps`` repetitions;
  * a linearity guard re-runs the same config compiled at half the scan
    length and requires the wall-clock GROWTH between the two lengths to
    clear 4x the repetition noise floor (median absolute deviation). If
    the timed region does not scale with the computation the measurement
    is *invalid*: the bench retries once (tunnel hiccup tolerance), then
    exits non-zero rather than print a fabricated number;
  * per-step FLOPs/bytes come from analytic kernel-shape models
    (``_analytic_step_flops`` / ``_analytic_step_bytes`` — XLA's
    ``cost_analysis()`` counts scan/map bodies once regardless of trip
    count, so it is reported but never used as per-step work), and MFU/MBU
    are reported against the detected chip's published peaks — a steps/sec
    claim that implies >100% utilization is impossible and the guard above
    would have caught it.

``vs_baseline`` is the MEASURED ratio: both implementations timed at the
largest size the PyTorch reference (CPU) can feasibly run, no extrapolation.
The extrapolated headline-scale ratio is reported separately with its
linearity caveat. Reference timings are cached in ``bench_baseline.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

BASELINE_CACHE = os.path.join(os.path.dirname(__file__), "bench_baseline.json")

# published per-chip peaks: ONE table, owned by coda_tpu/telemetry/costs.py
# (the roofline classifier serve /stats and the suite cost book share).
# The incremental EIG is bandwidth-bound — its per-round FLOP/byte ratio
# is ~19-32 at the headline config, far below a v5e's ~240 FLOP/byte
# machine balance — so MBU against the HBM peak, not MFU against the
# matmul peak, is the roofline that describes it.
from coda_tpu.telemetry.costs import (  # noqa: E402
    PEAK_FLOPS as _PEAK_FLOPS,
    PEAK_HBM_BPS as _PEAK_HBM_BPS,
)

# measured-at-size protocol constants: FIXED regardless of --small/--iters so
# the same-named metric always means the same measurement
MATCHED_ITERS = 100
REF_SIZES = [(25, 1250), (50, 2500), (100, 5000)]
REF_STEPS = 5


def _build_fn(H: int, N: int, C: int, iters: int, eig_chunk: int,
              eig_opts: dict | None = None):
    """(jitted experiment fn, (preds, labels)) for one config.

    ``eig_opts``: CODAHyperparams overrides (eig_mode / eig_backend /
    eig_precision) carried as one dict so a new knob doesn't have to thread
    through every bench signature.
    """
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.loop import build_experiment_fn
    from coda_tpu.oracle import true_losses
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = make_synthetic_task(seed=0, H=H, N=N, C=C)
    hp = CODAHyperparams(eig_chunk=eig_chunk, **(eig_opts or {}))

    # Build the selector INSIDE the jitted function so the (H, N, C) tensor
    # is a traced argument, not a baked-in constant (2 GB of captured
    # constants at M=1k, N=50k would bloat lowering and HBM).
    def run(preds, labels, key):
        import jax.numpy as jnp

        res = build_experiment_fn(
            make_coda(preds, hp), labels, true_losses(preds, labels),
            iters=iters,
        )(key)
        # pack the full result tree into ONE device buffer: every host
        # materialization pays a fixed per-buffer latency (~65 ms through
        # the axon tunnel), so 8 leaves cost ~0.5 s of pure transfer
        # latency per invocation. All int traces (idx < N, classes < C,
        # model ids < H) are exact in f32. The pack is part of the timed
        # program; nothing of the experiment itself changes.
        traces = jnp.stack([x.astype(jnp.float32) for x in
                            (res.chosen_idx, res.true_class, res.best_model,
                             res.regret, res.cumulative_regret,
                             res.select_prob)])
        scalars = jnp.stack([res.regret_at_0.astype(jnp.float32),
                             res.stochastic.astype(jnp.float32)])
        return jnp.concatenate([traces.ravel(), scalars])

    return jax.jit(run), (task.preds, task.labels)


def _compile(fn, args):
    """AOT-compile once; the same executable is timed and cost-analyzed."""
    import jax

    return fn.lower(*args, jax.random.PRNGKey(0)).compile()


def _timed_reps(compiled, args, reps: int) -> list[float]:
    """Wall-clock of ``reps`` runs, each materializing the FULL result tree."""
    import jax

    def once(seed: int) -> float:
        key = jax.random.PRNGKey(seed)
        t0 = time.perf_counter()
        out = compiled(*args, key)
        jax.tree.map(np.asarray, out)  # host copy of every leaf
        return time.perf_counter() - t0

    once(0)  # warm-up run of the same executable
    return [once(1 + r) for r in range(reps)]


def _flops_of(compiled) -> float:
    """XLA cost-model FLOPs — informational ONLY, structurally incomparable
    to per-step work: verified on this stack that (a) scan bodies are
    counted once, NOT multiplied by trip count (the value is identical for
    25- and 50-round programs), and (b) the same applies to every
    ``lax.map`` chunk loop INSIDE a step (one (B, ...) block counted, not
    N/B of them), while init-time work (cache/confusion build) IS included.
    The number therefore mixes under- and over-counting and can land on
    either side of the true per-step cost (observed 145 GF on TPU vs 108 GF
    on CPU for the same headline program whose corrected analytic per-step
    cost is 82.8 GF). MFU/MBU use :func:`_analytic_step_flops` /
    :func:`_analytic_step_bytes`; this field is kept for cross-checking
    orders of magnitude only.
    """
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older API: one dict per program
            cost = cost[0] if cost else {}
        return float(cost.get("flops", 0.0))
    except Exception as e:  # pragma: no cover
        print(f"[bench] cost_analysis unavailable: {e}", file=sys.stderr)
        return 0.0


def _analytic_step_flops(H: int, N: int, C: int, G: int = 256,
                         mode: str = "auto",
                         eig_cache_dtype: str = "float32",
                         pi_update: str = "auto",
                         posterior: str = "dense",
                         eig_scorer: str = "exact") -> tuple:
    """(flops_per_step, resolved_mode, resolved_pi_update) from the
    kernels' documented shapes.

    The mode and pi-hat path are resolved by the SAME functions
    ``make_coda`` uses (``resolve_eig_mode`` / ``resolve_pi_update``), so
    the reported FLOPs always describe the kernels that actually ran. Per
    round:

    Incremental EIG:
      * cache row refresh: three (N,H)x(H,G)-shaped einsums  -> 6·N·H·G
        (``update_eig_cache`` touches ONE class row per round)
      * pi-hat refresh: delta gather + sum over models       -> 2·H·N
        (``update_pi_hat_column_delta`` — 'auto' everywhere but
        multi-device TPU: pallas DMA gather on one chip, XLA
        take-along on CPU), or the exact column einsum
        hs,hns->n over the full tensor                       -> 2·H·N·C
        (``update_pi_hat_column``, 'auto' on multi-device TPU)
      * cache scoring (elementwise mixture entropies)        -> ~10·N·C·H
    Factored / rowscan EIG: the three einsums span all C class rows
    (identical FLOPs, different temps)                       -> 6·N·C·H·G
    plus the full pi-hat re-estimate hcs,hns->nc             -> 2·H·C²·N.
    """
    from coda_tpu.selectors import CODAHyperparams
    from coda_tpu.selectors.coda import resolve_eig_mode, resolve_pi_update

    # resolve with the SAME hyperparams the benched selector uses — the
    # cache dtype AND the posterior representation change the auto budget
    # (the dense (H, C, C) carry is charged; sparse:K is what keeps large-C
    # shapes inside the incremental tier), so omitting either here could
    # report a different tier than the one that ran
    hp = CODAHyperparams(eig_mode=mode, num_points=G,
                         eig_cache_dtype=eig_cache_dtype,
                         pi_update=pi_update, posterior=posterior,
                         eig_scorer=eig_scorer)
    mode = resolve_eig_mode(hp, H, N, C)
    pi_res = resolve_pi_update(hp, N)
    if mode == "incremental":
        pi_flops = (2.0 * H * N if pi_res.startswith("delta")
                    else 2.0 * H * N * C)
        # the scoring pass: the exact chain sweeps the whole cache; the
        # surrogate sweeps only its shortlist + audit rows through the
        # exact chain and prices O(N·F) features + the kc-gather + the
        # normal-equation refold on top (steady state — warmup/fallback
        # rounds pay the full pass, <= 10% of rounds by the committed
        # contract). The feature/fit cost applies to EVERY surrogate
        # round — including the k >= N parity configuration, whose
        # shortlist covers the pool but whose ridge still runs.
        score_rows = _scorer_rows(hp.eig_scorer, N)
        from coda_tpu.selectors.surrogate import (
            N_FEATURES,
            SURROGATE_FEATURE_KC,
            parse_scorer,
        )

        feat_flops = (0.0 if parse_scorer(hp.eig_scorer) is None else
                      2.0 * N * N_FEATURES * (N_FEATURES + 1)
                      + 3.0 * N * min(SURROGATE_FEATURE_KC, C) * H)
        return (6.0 * N * H * G + pi_flops + 10.0 * score_rows * C * H
                + feat_flops), mode, pi_res
    return 6.0 * N * C * H * G + 2.0 * H * C * C * N, mode, pi_res


def _scorer_rows(eig_scorer: str, N: int) -> int:
    """Rows the scoring pass streams through the exact chain per round:
    N for the exact scorer, shortlist+audit for the surrogate."""
    from coda_tpu.selectors.surrogate import (
        SURROGATE_AUDIT_ROWS,
        parse_scorer,
    )

    k = parse_scorer(eig_scorer)
    if k is None:
        return N
    return min(N, min(k, N) + SURROGATE_AUDIT_ROWS)


def _analytic_step_bytes(H: int, N: int, C: int, mode: str, *,
                         cache_bytes: int = 4,
                         pi_update: str, backend: str = "jnp",
                         eig_refresh: str = "precomputed",
                         posterior: str = "dense",
                         eig_scorer: str = "exact") -> float:
    """Analytic HBM traffic per round (bytes), for the bandwidth roofline.

    ``mode`` and ``pi_update`` must be the ALREADY-RESOLVED tier and
    pi-hat path (take them from :func:`_analytic_step_flops`'s return, so
    the FLOP and byte models can never describe different kernels).

    Incremental EIG per round: the scoring pass streams the (C, N, H)
    cache once at its storage width (``cache_bytes``: 4 fp32, 2 when
    eig_cache_dtype='bfloat16') — and with the (C, N, H) layout the
    physical HBM bytes match the logical count to ~H/ceil128(H) (the old
    (N, C, H) layout's 16-sublane pad at headline C=10 taxed every pass
    with 1.6x the logical bytes); the pi-hat refresh either gathers H
    contiguous N-rows from the loop-constant (C, H, N) fp32 layout
    (delta: 4·H·N bytes) or re-streams the full (H, N, C) tensor through
    the exact column einsum (exact: 4·H·N·C bytes — measured at ~88% of a
    v5e's HBM peak, which is why 'auto' picks it there); the cache row
    refresh reads the (N, H) int32 hard preds and writes the (N, H) row at
    cache width. The factored/rowscan tiers recompute from the full
    (H, N, C) tensor and stream the same-shaped fp32 hypothetical
    intermediates.
    """
    if mode == "incremental":
        # the scoring pass streams the cache rows it actually reads: all
        # N under the exact scorer, the shortlist + audit set under the
        # surrogate (steady state; warmup/fallback rounds stream it all),
        # plus the surrogate's O(N·kc·H) feature gather off the cache
        from coda_tpu.selectors.surrogate import (
            SURROGATE_FEATURE_KC,
            parse_scorer,
        )

        cache = float(cache_bytes) * _scorer_rows(eig_scorer, N) * C * H
        if parse_scorer(eig_scorer) is not None:
            cache += float(cache_bytes) * N * SURROGATE_FEATURE_KC * H
        pi_bytes = (4.0 * H * N if pi_update.startswith("delta")
                    else 4.0 * H * N * C)
        # posterior stream: the dense per-round Beta extraction reduces
        # the full (H, C, C) tensor (2 GB/round at ImageNet scale — the
        # term the sparse tier removes); sparse:K reads one compact row
        # (values + indices) and scatters it back. Negligible at the
        # C=10 headline, dominant at C=1000 — priced so the imagenet
        # config's MBU describes the kernel that actually runs.
        from coda_tpu.ops.sparse_rows import parse_posterior

        k = parse_posterior(posterior)
        post_bytes = (4.0 * H * C * C if k is None
                      else 16.0 * H * min(k, C))
        cache += post_bytes
        if backend == "pallas" and eig_refresh == "fused":
            # fused-COMPUTE refresh: the replacement row is computed
            # in-kernel from O(H·G) tables, so the (N, H) hyp_t round
            # trip is gone; the kernel reads the hard preds (int32) and
            # writes only the refreshed row at cache width
            return cache + pi_bytes + (4.0 + cache_bytes) * N * H
        if backend == "pallas":
            # fused refresh+score kernel: the donated cache is READ once;
            # only the refreshed (N, H) class row is written back (the
            # row-only aliased write — scalar-prefetch indexed BlockSpec),
            # and the replacement row makes one extra write+read round
            # trip ((N, H) fp32 out of the refresh einsums, into the
            # kernel); the hard-pred read feeds the refresh einsums as
            # before: 4 (hard preds) + 4 out + 4 in + cache_bytes written
            return cache + pi_bytes + (12.0 + cache_bytes) * N * H
        row = (4.0 + cache_bytes) * N * H
        return cache + pi_bytes + row
    hyp = 4.0 * N * C * H
    preds = 4.0 * H * N * C
    return hyp + preds + 8.0 * N * H


def _mad(xs: list[float]) -> float:
    """Median absolute deviation — robust to a single tunnel-hiccup outlier
    (observed: one rep in ~10 takes 6x the median through the axon tunnel)."""
    med = statistics.median(xs)
    return statistics.median(abs(x - med) for x in xs)


def bench_ours(H: int, N: int, C: int, iters: int, eig_chunk: int,
               reps: int = 5, eig_opts: dict | None = None) -> dict:
    """Trustworthy steps/sec: two scan lengths, marginal cost, FLOPs, MFU.

    The same experiment is compiled at ``iters`` and ``iters // 2`` scan
    steps and timed (median of ``reps``, full result-tree materialization).
    The DIFFERENCE isolates the marginal per-step cost from the fixed
    per-invocation cost (dispatch + host-transfer latency — ~65 ms per leaf
    through the experimental axon tunnel, which would otherwise dominate and
    hide whether the computation is being timed at all). ``linearity.ok``
    requires the wall-clock growth between the two lengths to clear the
    repetition noise — the guard that catches a clock which returns before
    the device queue drains.
    """
    import jax

    from coda_tpu.selectors import CODAHyperparams

    # normalize against the hyperparam defaults ONCE so the reported
    # metadata can never drift from what the selector actually ran with
    defaults = CODAHyperparams()._asdict()
    eig_opts = {**{k: defaults[k] for k in
                   ("eig_mode", "eig_backend", "eig_precision",
                    "eig_cache_dtype", "eig_refresh", "eig_entropy",
                    "posterior", "eig_pbest", "eig_scorer",
                    "pi_update")},
                **(eig_opts or {})}
    # _mad of a single rep is 0, which would floor the noise at 1e-12 and
    # let any positive wall-clock delta pass linear_ok; the guard only
    # discriminates with >= 2 reps (same reasoning as profile_step.py's
    # marginal_ms "resolved" logic).
    if reps < 2:
        print(f"[bench] reps={reps} raised to 2 (linearity guard needs "
              "spread)", file=sys.stderr)
        reps = 2
    half_iters = max(1, iters // 2)
    fn, data = _build_fn(H, N, C, iters, eig_chunk, eig_opts)
    compiled = _compile(fn, data)
    walls = _timed_reps(compiled, data, reps)
    fn_half, data_half = _build_fn(H, N, C, half_iters, eig_chunk, eig_opts)
    compiled_half = _compile(fn_half, data_half)
    walls_half = _timed_reps(compiled_half, data_half, reps)

    wall = statistics.median(walls)
    wall_half = statistics.median(walls_half)
    dw = wall - wall_half
    d_iters = iters - half_iters
    noise = max(_mad(walls), _mad(walls_half), 1e-12)
    linear_ok = dw > 0 and dw > 4.0 * noise

    marginal_step_s = dw / d_iters if d_iters else float("nan")
    overhead_s = wall - iters * marginal_step_s

    flops_per_step, mode, pi_res = _analytic_step_flops(
        H, N, C, mode=eig_opts["eig_mode"],
        eig_cache_dtype=eig_opts["eig_cache_dtype"],
        pi_update=eig_opts["pi_update"],
        posterior=eig_opts["posterior"],
        eig_scorer=eig_opts["eig_scorer"])
    # resolve the scoring backend with the SAME function make_coda uses
    # (and the same hyperparams _build_fn constructed) so the reported
    # metadata names the kernel that actually ran
    from coda_tpu.selectors.coda import resolve_eig_backend

    backend_res = resolve_eig_backend(
        CODAHyperparams(eig_chunk=eig_chunk, **eig_opts), mode)

    dev = jax.devices()[0]
    peak = _PEAK_FLOPS.get(dev.device_kind)
    peak_bw = _PEAK_HBM_BPS.get(dev.device_kind)
    # the machine-readable cost section (telemetry/costs.py): XLA's own
    # analysis of the timed executable (program-level; scan bodies counted
    # once — see _flops_of) plus the roofline classification of the
    # ANALYTIC per-step model, which is the honest per-round
    # flops/bytes pair. Harvested into the process cost book too, so a
    # --telemetry-dir run carries it in telemetry.json.
    from coda_tpu.telemetry import costs as _costs

    xla_cost = _costs.harvest_executable_cost(
        compiled, f"bench/coda/{H}x{N}x{C}/i{iters}", site="bench",
        device_kind=dev.device_kind,
        extra={"H": H, "N": N, "C": C, "iters": iters})
    if xla_cost is None:  # harvesting disabled/unavailable: analyze once
        xla_cost = _costs.analyze_compiled(compiled) or {}
    bytes_per_step = _analytic_step_bytes(
        H, N, C, mode=mode,
        cache_bytes=np.dtype(eig_opts["eig_cache_dtype"]).itemsize,
        pi_update=pi_res, backend=backend_res,
        eig_refresh=eig_opts["eig_refresh"],
        posterior=eig_opts["posterior"],
        eig_scorer=eig_opts["eig_scorer"])
    achieved = (flops_per_step / marginal_step_s
                if linear_ok and marginal_step_s > 0 else 0.0)
    achieved_bps = (bytes_per_step / marginal_step_s
                    if linear_ok and marginal_step_s > 0 else 0.0)
    return {
        "steps_per_sec": iters / wall,
        "marginal_steps_per_sec": (1.0 / marginal_step_s
                                   if marginal_step_s > 0 else 0.0),
        "fixed_overhead_s": round(overhead_s, 4),
        "wall_s_median": wall,
        "wall_s_all": [round(w, 4) for w in walls],
        "reps": reps,
        "iters": iters,
        "linearity": {
            "half_iters": half_iters,
            "wall_s_half": round(wall_half, 4),
            "wall_s_half_all": [round(w, 4) for w in walls_half],
            "delta_s": round(dw, 4),
            "rep_noise_s": round(noise, 4),
            "ok": linear_ok,
        },
        "eig_mode": mode,
        "eig_backend": backend_res,
        "eig_precision": eig_opts["eig_precision"],
        "eig_cache_dtype": eig_opts["eig_cache_dtype"],
        "eig_refresh": eig_opts["eig_refresh"],
        "eig_entropy": eig_opts["eig_entropy"],
        "posterior": eig_opts["posterior"],
        "eig_pbest": eig_opts["eig_pbest"],
        "eig_scorer": eig_opts["eig_scorer"],
        "pi_update": pi_res,
        "flops_per_step_analytic": flops_per_step,
        "flops_xla_scan_body_once": _flops_of(compiled),
        # MFU/MBU denominators are the ANALYTIC per-step models: the XLA
        # cost counter counts scan and lax.map bodies once regardless of
        # trip count (see _flops_of), so it is not per-step work
        "flop_accounting": "analytic",
        "achieved_flops_per_sec": achieved,
        "bytes_per_step_analytic": bytes_per_step,
        "achieved_bytes_per_sec": achieved_bps,
        "peak_hbm_bytes_per_sec": peak_bw,
        "mbu": (achieved_bps / peak_bw) if (peak_bw and achieved_bps)
               else None,
        "device_kind": dev.device_kind,
        "n_devices": len(jax.devices()),
        "platform": dev.platform,
        "peak_flops_per_sec": peak,
        "mfu": (achieved / peak) if (peak and achieved) else None,
        "cost": {
            # whole-program XLA analysis of the timed executable
            "xla_flops": xla_cost.get("flops"),
            "xla_bytes_accessed": xla_cost.get("bytes_accessed"),
            "argument_bytes": xla_cost.get("argument_bytes"),
            "output_bytes": xla_cost.get("output_bytes"),
            "temp_bytes": xla_cost.get("temp_bytes"),
            "peak_hbm_bytes": xla_cost.get("peak_hbm_bytes"),
            # per-step roofline off the analytic models (the MFU/MBU
            # numerators above); class vs the shared peak table, with a
            # documented generic host balance on unknown device kinds
            **_costs.roofline(flops_per_step, bytes_per_step,
                              dev.device_kind),
            "flop_accounting": "analytic_per_step",
        },
    }


def measure_reference_at(H: int, N: int, C: int,
                         steps: int = REF_STEPS) -> float:
    """Raw steps/sec of the PyTorch reference (CPU) at this exact size.

    Imports the read-only reference checkout if available; returns 0.0 when
    it isn't (ratios are then reported as 0.0 = unknown).
    """
    ref_path = "/root/reference"
    if not os.path.isdir(ref_path):
        return 0.0
    sys.path.insert(0, ref_path)
    try:
        import torch

        from coda.coda import CODA as RefCODA  # reference package

        from coda_tpu.data import make_synthetic_task

        task = make_synthetic_task(seed=0, H=H, N=N, C=C)

        class _DS:
            preds = torch.from_numpy(np.asarray(task.preds)).float()
            labels = torch.from_numpy(np.asarray(task.labels))

        sel = RefCODA(_DS())
        labels = np.asarray(task.labels)
        t0 = time.perf_counter()
        for _ in range(steps):
            idx, prob = sel.get_next_item_to_label()
            sel.add_label(int(idx), int(labels[int(idx)]), prob)
            sel.get_best_model_prediction()
        wall = time.perf_counter() - t0
        return steps / wall
    except Exception as e:  # pragma: no cover
        print(f"[bench] reference baseline unavailable: {e}", file=sys.stderr)
        return 0.0
    finally:
        sys.path.remove(ref_path)


def reference_baseline(C: int, skip: bool) -> dict:
    """Multi-size reference measurements + linear H*N extrapolation check.

    Returns {sizes: {key: steps_per_sec}, linearity_dev, k_mean} where
    k = steps_per_sec * H * N is the per-size proportionality constant and
    linearity_dev = (max k - min k) / mean k across sizes (small dev =>
    the linear extrapolation to headline scale is empirically grounded).
    Measurements are cached in bench_baseline.json; delete it to re-measure.
    """
    cache = {}
    if os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            cache = json.load(f)
    sizes = cache.setdefault("sizes", {})
    dirty = False
    for h, n in REF_SIZES:
        key = f"h{h}_n{n}_c{C}"
        if key not in sizes:
            if skip:
                return {}
            sps = measure_reference_at(h, n, C)
            if sps <= 0.0:
                return {}
            sizes[key] = {"steps_per_sec": sps, "steps": REF_STEPS,
                          "H": h, "N": n, "C": C}
            dirty = True
    if dirty:
        with open(BASELINE_CACHE, "w") as f:
            json.dump(cache, f, indent=2)
    ks = [v["steps_per_sec"] * v["H"] * v["N"]
          for v in sizes.values() if v["C"] == C]
    k_mean = sum(ks) / len(ks)
    return {
        "sizes": {k: v for k, v in sizes.items() if v["C"] == C},
        "k_mean": k_mean,
        "linearity_dev": (max(ks) - min(ks)) / k_mean,
    }


def _probe_devices(timeout_s: float = 90.0):
    """None if a jax array op completes in a fresh subprocess, else a
    short reason string.

    The environment's site hook registers an experimental device tunnel;
    when that tunnel is wedged, ANY jax array op hangs the process forever
    — including this bench, which would then produce nothing at all. The
    probe runs in a subprocess so the hang is bounded by a timeout. A
    crash (nonzero exit) is reported distinctly from a hang, with the
    child's stderr tail surfaced.
    """
    import subprocess

    code = ("import numpy as np, jax, jax.numpy as jnp;"
            "np.asarray(jnp.ones(2) + 1)")
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
    except subprocess.TimeoutExpired:
        return f"probe hung (> {timeout_s:.0f}s)"
    if r.returncode == 0:
        return None
    tail = r.stderr.decode(errors="replace").strip().splitlines()[-3:]
    return (f"probe crashed (exit {r.returncode}): " + " | ".join(tail))


# named shape presets: (H, N, C, iters, chunk). "imagenet" reproduces the
# IMAGENET_VIRTUAL_r05.json pool shape (C=1000, H=500, N scaled to one
# host) so the large-C capture is one flag; "imagenet_smoke" is its
# scaled-down-C stand-in for the quick evidence run (same tier/kernels,
# container-sized init cost). Both are INIT-DOMINATED: the one-time
# incremental cache build dwarfs the rounds, so the linearity guard
# reports instead of failing there (the committed round-time evidence for
# the shape lives in IMAGENET_SPARSE_*.json, measured with a 50-round
# delta by scripts/imagenet_sparse.py).
BENCH_CONFIGS = {
    "headline": (1000, 50_000, 10, 50, 2048),
    "small": (32, 2000, 10, 10, 1000),
    "imagenet": (500, 256, 1000, 10, 64),
    "imagenet_smoke": (50, 256, 100, 10, 64),
}
_GUARD_SOFT_CONFIGS = ("small", "imagenet", "imagenet_smoke")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None, choices=sorted(BENCH_CONFIGS),
                    help="named shape preset (default: headline; "
                         "'imagenet' = the C=1000/H=500 pool of "
                         "IMAGENET_VIRTUAL_r05.json, 'imagenet_smoke' = "
                         "its scaled-down-C quick-evidence stand-in)")
    ap.add_argument("--small", action="store_true",
                    help="small smoke config instead of the headline M=1k,N=50k")
    ap.add_argument("--iters", type=int, default=None,
                    help="override headline scan length (matched-size "
                         "measurement stays fixed at %d)" % MATCHED_ITERS)
    ap.add_argument("--reps", type=int, default=5,
                    help="timed repetitions per config (minimum 2: the "
                         "MAD linearity guard needs spread)")
    ap.add_argument("--eig-mode", default="auto",
                    help="force a CODA EIG kernel tier (for comparisons); "
                         "auto = incremental when its cache fits")
    ap.add_argument("--eig-backend", default="auto",
                    help="incremental-EIG scoring backend: auto (pallas on "
                         "a single-chip TPU process, jnp elsewhere) | jnp | "
                         "pallas (fused single-HBM-pass TPU kernel)")
    ap.add_argument("--eig-precision", default="highest",
                    choices=["highest", "high", "default"],
                    help="EIG table-einsum matmul precision: highest "
                         "(reference numerics) | high | default — below "
                         "highest is an opt-in speed/parity tradeoff")
    ap.add_argument("--eig-cache-dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="storage dtype of the incremental P(best) cache "
                         "(bfloat16 halves the dominant HBM stream; "
                         "opt-in numerics like --eig-precision)")
    ap.add_argument("--eig-refresh", default="precomputed",
                    choices=["precomputed", "fused"],
                    help="incremental row-refresh: precomputed (XLA-"
                         "HIGHEST einsums, reference numerics) | fused "
                         "(in-kernel MXU dots overlap the cache read; "
                         "opt-in numerics, pallas backend only)")
    ap.add_argument("--eig-entropy", default="exact",
                    choices=["exact", "approx"],
                    help="log lowering of the scoring pass's expected-"
                         "entropy chain: exact (transcendental, reference "
                         "numerics) | approx (bit-manip + polynomial "
                         "log2, max |Dscore| <= 1e-4 — the knob for the "
                         "bf16 <= 2.2 ms target; opt-in numerics)")
    ap.add_argument("--eig-chunk", type=int, default=0,
                    help="override the scoring-pass block size (0 = the "
                         "config default; the tuning knob for the "
                         "cache-stream pass)")
    ap.add_argument("--posterior", default="dense",
                    metavar="dense|sparse:K",
                    help="Dirichlet posterior representation: sparse:K "
                         "carries top-K class rows + residual instead of "
                         "the dense (H, C, C) tensor (the large-C rung; "
                         "see --posterior on the main CLI)")
    ap.add_argument("--eig-scorer", default="exact",
                    metavar="exact|surrogate:k",
                    help="who scores the round: exact (full O(N*C*H) "
                         "chain) | surrogate:k (carried ridge scores all "
                         "N, exact chain refreshes only the top-k "
                         "shortlist + audit set under the measured "
                         "contract — see the main CLI's --eig-scorer)")
    ap.add_argument("--eig-pbest", default="quad",
                    choices=["quad", "amortized"],
                    help="row-refresh P(best) integral: quad (reference "
                         "Beta quadrature) | amortized (closed-form "
                         "logistic-normal tables where the concentration "
                         "gate holds the 2.34e-4 contract)")
    ap.add_argument("--pi-update", default="auto",
                    choices=["auto", "delta", "exact"],
                    help="incremental pi-hat refresh: auto (default) = "
                         "delta (pallas DMA gather on a single TPU chip, "
                         "XLA take-along on CPU) / exact on multi-device "
                         "TPU")
    ap.add_argument("--skip-reference", action="store_true")
    ap.add_argument("--no-device-probe", action="store_true",
                    help="skip the pre-flight subprocess probe of the "
                         "accelerator (and its CPU fallback)")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (cpu/tpu); skips the probe")
    args = ap.parse_args()

    from coda_tpu.utils.platform import pin_platform

    pin_platform(args.platform)
    device_fallback = None
    already_pinned = args.platform or (
        "jax" in sys.modules
        and sys.modules["jax"].config.jax_platforms == "cpu")
    reason = None
    if not args.no_device_probe and not already_pinned:
        reason = _probe_devices()
    if reason is not None:
        # a wedged tunnel hangs every jax op; a bounded CPU measurement
        # with an explicit marker beats an unbounded hang with no output
        print(f"[bench] device {reason} — measuring on CPU with an "
              "explicit marker", file=sys.stderr)
        pin_platform("cpu")
        device_fallback = f"{reason}; measured on CPU"
        # the CPU fallback at headline scale runs ~3 s/step; cap the reps
        # so the whole protocol stays within a plausible driver timeout
        args.reps = min(args.reps, 3)

    config = args.config or ("small" if args.small else "headline")
    guard_soft = config in _GUARD_SOFT_CONFIGS
    H, N, C, iters, chunk = BENCH_CONFIGS[config]
    if args.eig_chunk:
        chunk = args.eig_chunk

    # one retry if the linearity guard trips: a single tunnel hiccup can
    # blow the noise floor of one rep set, and re-measuring is cheaper and
    # more honest than discarding the whole round. A SECOND failure means
    # the protocol genuinely can't resolve the per-step cost — report
    # invalid as before.
    eig_opts = {"eig_mode": args.eig_mode, "eig_backend": args.eig_backend,
                "eig_precision": args.eig_precision,
                "eig_cache_dtype": args.eig_cache_dtype,
                "eig_refresh": args.eig_refresh,
                "eig_entropy": args.eig_entropy,
                "posterior": args.posterior,
                "eig_pbest": args.eig_pbest,
                "eig_scorer": args.eig_scorer,
                "pi_update": args.pi_update}
    for attempt in range(2):
        ours = bench_ours(H, N, C, iters=args.iters or iters, eig_chunk=chunk,
                          reps=args.reps, eig_opts=eig_opts)
        if ours["linearity"]["ok"] or guard_soft:
            break
        print("[bench] linearity guard tripped on attempt "
              f"{attempt + 1}; " + ("re-measuring" if attempt == 0 else
                                    "giving up — reporting invalid"),
              file=sys.stderr)

    # the torch reference has no business at the imagenet presets (its
    # extrapolated round time there is hours; the r05 artifact is the
    # committed baseline for that shape)
    base = reference_baseline(C, skip=args.skip_reference
                              or config.startswith("imagenet"))
    # environment fingerprint (telemetry/recorder.py): the provenance
    # block that makes this capture attributable and cross-round
    # comparable — scripts/check_perf.py keys same-fingerprint regression
    # comparisons on it
    from coda_tpu.telemetry.recorder import environment_fingerprint

    fingerprint = environment_fingerprint(
        knobs=dict(eig_opts, iters=args.iters or iters, config=config,
                   small=config == "small", eig_chunk=chunk))
    out = {
        "metric": f"coda-selection-steps/sec (M={H}, N={N}, C={C})",
        "config": config,
        "value": round(ours["steps_per_sec"], 4),
        "unit": "steps/sec",
        "vs_baseline": 0.0,
        "marginal_steps_per_sec": round(ours["marginal_steps_per_sec"], 4),
        "fixed_overhead_s": ours["fixed_overhead_s"],
        "timing": {k: ours[k] for k in
                   ("wall_s_median", "wall_s_all", "reps", "iters",
                    "linearity")},
        "devices": {k: ours[k] for k in
                    ("device_kind", "n_devices", "platform")},
        "device_fallback": device_fallback,
        "cost": ours["cost"],
        "fingerprint": fingerprint,
        "compute": {k: ours[k] for k in
                    ("eig_mode", "eig_backend", "eig_precision",
                     "eig_cache_dtype", "eig_refresh", "eig_entropy",
                     "posterior", "eig_pbest", "eig_scorer", "pi_update",
                     "flops_per_step_analytic", "flop_accounting",
                     "flops_xla_scan_body_once", "achieved_flops_per_sec",
                     "peak_flops_per_sec", "mfu",
                     "bytes_per_step_analytic", "achieved_bytes_per_sec",
                     "peak_hbm_bytes_per_sec", "mbu")},
    }
    if base:
        # PRIMARY ratio: both implementations measured at the same size, no
        # extrapolation, fixed per-call overhead INCLUDED on our side (the
        # conservative choice). The reference cannot feasibly run the
        # headline config (extrapolated ~1.2e-4 steps/sec => days per run).
        hm, nm = REF_SIZES[-1]
        ref_matched = base["sizes"][f"h{hm}_n{nm}_c{C}"]["steps_per_sec"]
        ours_matched = bench_ours(hm, nm, C, iters=MATCHED_ITERS,
                                  eig_chunk=chunk, reps=args.reps,
                                  eig_opts=eig_opts)
        out["vs_baseline"] = round(
            ours_matched["steps_per_sec"] / ref_matched, 4)
        out["vs_baseline_measured_at"] = (
            f"M={hm}, N={nm}, C={C}, iters={MATCHED_ITERS}")
        out["ours_measured_at_size_steps_per_sec"] = round(
            ours_matched["steps_per_sec"], 4)
        out["matched_linearity_ok"] = ours_matched["linearity"]["ok"]
        if ours_matched["linearity"]["ok"]:
            # marginal (overhead-subtracted) ratio, only when the per-step
            # delta actually cleared the noise floor at this size — at
            # matched size the incremental EIG's per-step cost can be
            # MICROseconds, below what the tunnel's jitter resolves
            out["ours_measured_at_size_marginal"] = round(
                ours_matched["marginal_steps_per_sec"], 4)
            out["vs_baseline_marginal"] = round(
                ours_matched["marginal_steps_per_sec"] / ref_matched, 4)

        # SECONDARY: extrapolated ratio at headline scale (k_mean / H*N),
        # with the reference's own linearity spread as the caveat
        ref_extrap = base["k_mean"] / (H * N)
        out["vs_baseline_extrapolated"] = round(
            ours["steps_per_sec"] / ref_extrap, 4)
        out["ref_extrapolated_steps_per_sec"] = ref_extrap
        out["ref_linearity_dev"] = round(base["linearity_dev"], 4)

    print(json.dumps(out))
    if not ours["linearity"]["ok"]:
        msg = (
            "[bench] wall-clock growth between scan lengths "
            f"(delta {ours['linearity']['delta_s']}s) does not clear the "
            f"repetition noise ({ours['linearity']['rep_noise_s']}s): the "
            "per-step compute is not resolvable against the fixed "
            "per-invocation overhead"
        )
        if guard_soft:
            # the smoke config's per-step work is micro-seconds (and the
            # imagenet presets are init-dominated); only warn — their
            # committed round-time evidence uses the 50-round delta of
            # scripts/imagenet_sparse.py instead
            print(msg + f" (expected for --config {config})",
                  file=sys.stderr)
        else:
            print(msg + " — timing INVALID at headline scale; refusing to "
                  "report this as real", file=sys.stderr)
            sys.exit(1)


if __name__ == "__main__":
    main()
