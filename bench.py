"""Benchmark: CODA selection-steps/sec on the current accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The headline config follows BASELINE.json (selection-steps/sec at M=1k
models, N=50k points); ``--small`` runs a reduced config for smoke tests.
``vs_baseline`` compares against the PyTorch reference implementation's
measured per-step wall-clock on this machine's CPU (the reference has no
published speed numbers — see BASELINE.md). The reference timing is cached
in ``bench_baseline.json`` after the first measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BASELINE_CACHE = os.path.join(os.path.dirname(__file__), "bench_baseline.json")


def bench_ours(H: int, N: int, C: int, iters: int, eig_chunk: int) -> float:
    """Returns selection steps/sec for a compiled CODA experiment."""
    import jax

    from coda_tpu.data import make_synthetic_task
    from coda_tpu.engine.loop import build_experiment_fn
    from coda_tpu.oracle import true_losses
    from coda_tpu.selectors import CODAHyperparams, make_coda

    task = make_synthetic_task(seed=0, H=H, N=N, C=C)
    hp = CODAHyperparams(eig_chunk=eig_chunk)

    # Build the selector INSIDE the jitted function so the (H, N, C) tensor
    # is a traced argument, not a baked-in constant (2 GB of captured
    # constants at M=1k, N=50k would bloat lowering and HBM).
    def run(preds, labels, key):
        sel = make_coda(preds, hp)
        losses = true_losses(preds, labels)
        return build_experiment_fn(sel, labels, losses, iters=iters)(key)

    import numpy as np

    fn = jax.jit(run)
    # jit ONCE; warm-up hits the same compiled executable as the measurement.
    # Time through a host read of the result: on the experimental axon TPU
    # tunnel, block_until_ready alone can return before the queue flushes.
    np.asarray(fn(task.preds, task.labels, jax.random.PRNGKey(0)).regret)
    t0 = time.perf_counter()
    np.asarray(fn(task.preds, task.labels, jax.random.PRNGKey(1)).regret)
    wall = time.perf_counter() - t0
    return iters / wall


# Reference measurement sizes: per-step cost is ~linear in H*N, so three
# sizes spanning 16x in H*N validate the extrapolation empirically before it
# is trusted at the headline scale. The largest is also the matched size for
# the measured-at-size (no-extrapolation) ratio.
REF_SIZES = [(25, 1250), (50, 2500), (100, 5000)]
REF_STEPS = 5


def measure_reference_at(H: int, N: int, C: int,
                         steps: int = REF_STEPS) -> float:
    """Raw steps/sec of the PyTorch reference (CPU) at this exact size.

    Imports the read-only reference checkout if available; returns 0.0 when
    it isn't (ratios are then reported as 0.0 = unknown).
    """
    ref_path = "/root/reference"
    if not os.path.isdir(ref_path):
        return 0.0
    sys.path.insert(0, ref_path)
    try:
        import numpy as np
        import torch

        from coda.coda import CODA as RefCODA  # reference package

        from coda_tpu.data import make_synthetic_task

        task = make_synthetic_task(seed=0, H=H, N=N, C=C)

        class _DS:
            preds = torch.from_numpy(np.asarray(task.preds)).float()
            labels = torch.from_numpy(np.asarray(task.labels))

        sel = RefCODA(_DS())
        labels = np.asarray(task.labels)
        t0 = time.perf_counter()
        for _ in range(steps):
            idx, prob = sel.get_next_item_to_label()
            sel.add_label(int(idx), int(labels[int(idx)]), prob)
            sel.get_best_model_prediction()
        wall = time.perf_counter() - t0
        return steps / wall
    except Exception as e:  # pragma: no cover
        print(f"[bench] reference baseline unavailable: {e}", file=sys.stderr)
        return 0.0
    finally:
        sys.path.remove(ref_path)


def reference_baseline(C: int, skip: bool) -> dict:
    """Multi-size reference measurements + linear H*N extrapolation check.

    Returns {sizes: {key: steps_per_sec}, linearity_dev, k_mean} where
    k = steps_per_sec * H * N is the per-size proportionality constant and
    linearity_dev = (max k - min k) / mean k across sizes (small dev =>
    the linear extrapolation to headline scale is empirically grounded).
    Measurements are cached in bench_baseline.json; delete it to re-measure.
    """
    cache = {}
    if os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            cache = json.load(f)
    sizes = cache.setdefault("sizes", {})
    dirty = False
    for h, n in REF_SIZES:
        key = f"h{h}_n{n}_c{C}"
        if key not in sizes:
            if skip:
                return {}
            sps = measure_reference_at(h, n, C)
            if sps <= 0.0:
                return {}
            sizes[key] = {"steps_per_sec": sps, "steps": REF_STEPS,
                          "H": h, "N": n, "C": C}
            dirty = True
    if dirty:
        with open(BASELINE_CACHE, "w") as f:
            json.dump(cache, f, indent=2)
    ks = [v["steps_per_sec"] * v["H"] * v["N"]
          for v in sizes.values() if v["C"] == C]
    k_mean = sum(ks) / len(ks)
    return {
        "sizes": {k: v for k, v in sizes.items() if v["C"] == C},
        "k_mean": k_mean,
        "linearity_dev": (max(ks) - min(ks)) / k_mean,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="small smoke config instead of the headline M=1k,N=50k")
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--skip-reference", action="store_true")
    args = ap.parse_args()

    if args.small:
        H, N, C, iters, chunk = 32, 2000, 10, 10, 1000
    else:
        H, N, C, iters, chunk = 1000, 50_000, 10, 50, 2048

    steps_per_sec = bench_ours(H, N, C, iters=args.iters or iters,
                               eig_chunk=chunk)

    base = reference_baseline(C, skip=args.skip_reference)
    out = {
        "metric": f"coda-selection-steps/sec (M={H}, N={N}, C={C})",
        "value": round(steps_per_sec, 4),
        "unit": "steps/sec",
        "vs_baseline": 0.0,
    }
    if base:
        # extrapolated ratio at headline scale (k_mean / H*N), empirically
        # checked: linearity_dev is the spread of k over a 16x H*N range
        ref_extrap = base["k_mean"] / (H * N)
        out["vs_baseline"] = round(steps_per_sec / ref_extrap, 4)
        out["ref_extrapolated_steps_per_sec"] = ref_extrap
        out["ref_linearity_dev"] = round(base["linearity_dev"], 4)

        # measured-at-size ratio: both implementations at the largest size
        # the reference can feasibly run — no extrapolation involved
        hm, nm = REF_SIZES[-1]
        ref_matched = base["sizes"][f"h{hm}_n{nm}_c{C}"]["steps_per_sec"]
        ours_matched = bench_ours(hm, nm, C, iters=args.iters or iters,
                                  eig_chunk=chunk)
        out["vs_baseline_measured"] = round(ours_matched / ref_matched, 4)
        out["vs_baseline_measured_at"] = f"M={hm}, N={nm}, C={C}"
        out["ours_measured_at_size_steps_per_sec"] = round(ours_matched, 4)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
